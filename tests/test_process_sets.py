"""Process sets (post-v0.13 ``hvd.add_process_set`` + ``process_set=``;
the v0.13 reference fixes every collective to MPI_COMM_WORLD).
Single-process legs over the 8-replica CPU mesh; the cross-process legs
live in tests/test_multiprocess.py::test_process_sets_three_processes.
"""

import jax.numpy as jnp
import numpy as np
import pytest


def test_process_set_registration_and_identity(hvd):
    ps = hvd.add_process_set([5, 0, 2, 2])  # dedup + sort
    assert ps.ranks == (0, 2, 5)
    assert ps.size() == 3
    assert ps.included()
    assert isinstance(ps, hvd.ProcessSet)
    ps2 = hvd.add_process_set([1, 3])
    assert ps2.process_set_id == ps.process_set_id + 1
    with pytest.raises(ValueError, match="outside"):
        hvd.add_process_set([0, 99])
    with pytest.raises(ValueError, match="at least one"):
        hvd.add_process_set([])


def test_subset_allreduce_denominators(hvd):
    """Sum multiplies by the SET size, average divides by it — the set,
    not the world, is the communicator (Horovod's semantics)."""
    ps = hvd.add_process_set([0, 1, 2])
    x = jnp.array([2.0])
    assert float(hvd.allreduce(x, average=False, process_set=ps)[0]) == 6.0
    assert float(hvd.allreduce(x, average=True, process_set=ps)[0]) == 2.0
    assert float(hvd.allreduce(x, op=hvd.Product,
                               process_set=ps)[0]) == 8.0
    # Adasum needs a power-of-two SET size, regardless of world size.
    with pytest.raises(ValueError, match="power-of-two"):
        hvd.allreduce(x, op=hvd.Adasum, process_set=ps)
    ps4 = hvd.add_process_set([0, 1, 2, 3])
    assert float(hvd.allreduce(x, op=hvd.Adasum,
                               process_set=ps4)[0]) == pytest.approx(2.0)


def test_subset_ragged_allgather_and_broadcast(hvd):
    ps = hvd.add_process_set([1, 4, 6])
    out = np.asarray(hvd.allgather(
        [jnp.full((i + 1, 2), float(i)) for i in range(3)],
        process_set=ps))
    assert out.shape == (6, 2)
    np.testing.assert_allclose(out[0], 0.0)
    np.testing.assert_allclose(out[1:3], 1.0)
    np.testing.assert_allclose(out[3:], 2.0)
    # Broadcast root is the GLOBAL rank number (Horovod's convention).
    out = hvd.broadcast(jnp.arange(4.0), 6, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), [0, 1, 2, 3])
    with pytest.raises(ValueError, match="not a member"):
        hvd.broadcast(jnp.ones(2), 3, process_set=ps)


def test_subset_rejects_global_per_replica_shard(hvd):
    ps = hvd.add_process_set([0, 1])
    with pytest.raises(ValueError, match="sub-slicing"):
        hvd.allreduce(hvd.shard(jnp.ones((8, 2))), process_set=ps)


def test_subset_and_global_ops_interleave(hvd):
    """Set and world collectives share the queue and the drain loop but
    negotiate in separate coordinators — async handles from both resolve
    correctly."""
    ps = hvd.add_process_set([0, 3])
    h_set = hvd.allreduce_async(jnp.array([1.0]), average=False,
                                process_set=ps, name="mix.set")
    h_world = hvd.allreduce_async(jnp.array([1.0]), average=False,
                                  name="mix.world")
    assert float(hvd.synchronize(h_world)[0]) == float(hvd.size())
    assert float(hvd.synchronize(h_set)[0]) == 2.0


def test_subset_wire_roundtrip():
    from horovod_tpu.ops.wire import (DataType, ReduceOp, Request,
                                      RequestType, Response, ResponseType)

    r = Request(1, RequestType.ALLREDUCE, DataType.FLOAT32, "x",
                tensor_shape=(3,), reduce_op=ReduceOp.MAX,
                process_set_id=7)
    r2, _ = Request.unpack(r.pack())
    assert r2 == r
    resp = Response(ResponseType.ALLREDUCE, ["x"], process_set_id=7)
    resp2, _ = Response.unpack(resp.pack())
    assert resp2.process_set_id == 7


def test_set_output_chains_into_global_collective(hvd):
    """A set collective's output fed into a global one (and vice versa)
    must be re-placed, not crash with an incompatible-devices error
    (review finding: users naturally chain across communicators)."""
    ps = hvd.add_process_set([0, 1, 2])
    out = hvd.allreduce(jnp.array([1.0]), average=False, process_set=ps)
    world = hvd.allreduce(out, average=False)
    assert float(world[0]) == 3.0 * hvd.size()
    back = hvd.allreduce(world, average=True, process_set=ps)
    assert float(back[0]) == 3.0 * hvd.size()


def test_sparse_allreduce_respects_process_set(hvd):
    """IndexedSlices + process_set gathers over the SET and divides by
    the SET size (review finding: it silently ran global before)."""
    from horovod_tpu import IndexedSlices
    from horovod_tpu.ops.sparse import as_dense

    ps = hvd.add_process_set([0, 1, 2])
    sl = IndexedSlices(jnp.ones((2, 3)), jnp.array([0, 1]), (4, 3))
    out = hvd.allreduce(sl, average=False, process_set=ps)
    assert out.values.shape[0] == 2 * ps.size()  # 6 set rows, not 16
    dense = np.asarray(as_dense(out))
    np.testing.assert_allclose(dense[:2], 3.0)
    np.testing.assert_allclose(dense[2:], 0.0)
    out = hvd.allreduce(sl, average=True, process_set=ps)
    np.testing.assert_allclose(np.asarray(as_dense(out))[:2], 1.0)


def test_auto_names_namespaced_per_set(hvd):
    """Unnamed set ops consume a set-scoped counter, leaving the global
    counter untouched (review finding: desync across ranks otherwise)."""
    from horovod_tpu.ops.collective import _auto_name

    ps = hvd.add_process_set([0, 1])
    g1 = _auto_name("allreduce")
    s1 = _auto_name("allreduce", ps)
    g2 = _auto_name("allreduce")
    assert s1.startswith(f"ps{ps.process_set_id}.allreduce.noname.")
    # The global counter advanced by exactly one despite the set op.
    assert int(g2.rsplit(".", 1)[1]) == int(g1.rsplit(".", 1)[1]) + 1


def test_set_fusion_sizes_fall_back_to_shapes(make_coord=None):
    """A set coordinator polled with an empty size table (the controller
    is not a member, so ITS queue has no entries) still enforces the
    fusion threshold via shape-derived sizes (review finding)."""
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import (DataType, Request, RequestType)

    c = PyCoordinator(1, 100)  # threshold 100 bytes
    # Derived sizes: a=60B, b=60B (can't join a: 120 > 100), c=20B
    # (joins a: 80 <= 100).
    for name, dim in (("a", 15), ("b", 15), ("c", 5)):
        c.submit(Request(0, RequestType.ALLREDUCE, DataType.FLOAT32,
                         name, tensor_shape=(dim,), process_set_id=3))
    resps = c.poll_responses({})  # empty size table
    assert all(r.process_set_id == 3 for r in resps)
    groups = sorted(sorted(r.tensor_names) for r in resps)
    assert groups == [["a", "c"], ["b"]], groups


def test_remove_process_set_and_global_set(hvd):
    """remove_process_set deregisters (post-v0.13 API); the global set
    object is equivalent to process_set=None and cannot be removed."""
    ps = hvd.add_process_set([0, 1])
    assert hvd.remove_process_set(ps) is True
    assert hvd.remove_process_set(ps) is False  # already gone
    with pytest.raises(hvd.HorovodError, match="not registered"):
        hvd.allreduce(jnp.ones((1,)), process_set=ps, name="gone.set")

    g = hvd.global_process_set()
    assert g.process_set_id == 0 and g.size() == hvd.size()
    out = hvd.allreduce(jnp.array([1.0]), average=False, process_set=g)
    assert float(out[0]) == float(hvd.size())
    with pytest.raises(ValueError, match="cannot be removed"):
        hvd.remove_process_set(g)
