"""Tensor-parallel matmul tests: sharded results must equal the dense
single-device computation (self-verifying, SURVEY.md §4 style)."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import MODEL_AXIS, make_mesh
from horovod_tpu.parallel.tensor import (column_parallel,
                                         gather_column_parallel,
                                         local_shard, row_parallel,
                                         row_parallel_scatter, tp_mlp)

TOL = 1e-5


def _mesh(n=4):
    return make_mesh(model=n, devices=jax.devices()[:n])


def test_column_then_row_matches_dense():
    mesh = _mesh()
    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    x = jax.random.normal(k1, (8, 16))
    w1 = jax.random.normal(k2, (16, 32)) * 0.1
    b1 = jax.random.normal(k3, (32,)) * 0.1
    w2 = jax.random.normal(k4, (32, 16)) * 0.1
    b2 = jax.random.normal(k5, (16,)) * 0.1

    def tp(x, w1, b1, w2, b2):
        h = column_parallel(x, local_shard(w1, 1),
                            local_shard(b1, 0))
        h = jax.nn.gelu(h)
        return row_parallel(h, local_shard(w2, 0), b2)

    got = jax.jit(_compat.shard_map(
        tp, mesh=mesh, in_specs=(P(), P(), P(), P(), P()),
        out_specs=P(), check_vma=False))(x, w1, b1, w2, b2)
    want = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
    assert jnp.max(jnp.abs(got - want)) < TOL


def test_tp_mlp_helper_matches_dense():
    mesh = _mesh()
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (4, 8))
    w1 = jax.random.normal(k2, (8, 16)) * 0.1
    w2 = jax.random.normal(k3, (16, 8)) * 0.1

    def tp(x, w1, w2):
        return tp_mlp(x, local_shard(w1, 1), None, local_shard(w2, 0),
                      None)

    got = jax.jit(_compat.shard_map(tp, mesh=mesh, in_specs=(P(),) * 3,
                                out_specs=P(), check_vma=False))(x, w1, w2)
    want = jax.nn.gelu(x @ w1) @ w2
    assert jnp.max(jnp.abs(got - want)) < TOL


def test_column_parallel_gather_output():
    mesh = _mesh()
    x = jnp.eye(8)
    w = jnp.arange(8.0 * 8).reshape(8, 8)

    def tp(x, w):
        return column_parallel(x, local_shard(w, 1), gather_output=True)

    got = jax.jit(_compat.shard_map(tp, mesh=mesh, in_specs=(P(), P()),
                                out_specs=P(), check_vma=False))(x, w)
    assert jnp.max(jnp.abs(got - w)) < TOL


def test_row_parallel_unsharded_input():
    mesh = _mesh()
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (4, 16))
    w = jax.random.normal(jax.random.PRNGKey(3), (16, 8)) * 0.1

    def tp(x, w):
        return row_parallel(x, local_shard(w, 0),
                            input_is_parallel=False)

    got = jax.jit(_compat.shard_map(tp, mesh=mesh, in_specs=(P(), P()),
                                out_specs=P(), check_vma=False))(x, w)
    assert jnp.max(jnp.abs(got - x @ w)) < TOL


# ---------------------------------------------------------------------------
# hvd-fuse: fused computation-collective closers/openers
# ---------------------------------------------------------------------------


def _tp_mlp_bytes(fuse, fuse_chunks=None):
    mesh = _mesh()
    key = jax.random.PRNGKey(7)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (16, 8))
    w1 = jax.random.normal(k2, (8, 16)) * 0.1
    w2 = jax.random.normal(k3, (16, 8)) * 0.1

    def tp(x, w1, w2):
        return tp_mlp(x, local_shard(w1, 1), None, local_shard(w2, 0),
                      None, fuse=fuse, fuse_chunks=fuse_chunks)

    got = jax.jit(_compat.shard_map(tp, mesh=mesh, in_specs=(P(),) * 3,
                                    out_specs=P(), check_vma=False))(
        x, w1, w2)
    import numpy as np
    return np.asarray(got).tobytes()


@pytest.mark.parametrize("chunks", [2, 4])
def test_fused_row_parallel_bitwise_vs_unfused(chunks):
    # The fused (chunk-interleaved) psum closer must reproduce the
    # unfused reference program's bytes exactly.
    assert _tp_mlp_bytes(True, chunks) == _tp_mlp_bytes(False)


def test_fused_env_off_pins_reference(monkeypatch):
    from horovod_tpu.ops import fused as F
    monkeypatch.setenv(F.FUSE_ENV, "off")
    off = _tp_mlp_bytes(None)
    monkeypatch.setenv(F.FUSE_ENV, "on")
    on = _tp_mlp_bytes(None)
    assert off == on


def test_scatter_gather_pair_matches_dense():
    # row_parallel_scatter → gather_column_parallel: the feature-sharded
    # handoff must compose back to the dense two-block computation.
    mesh = _mesh()
    key = jax.random.PRNGKey(8)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (8, 16))
    w1 = jax.random.normal(k2, (16, 16)) * 0.1
    w2 = jax.random.normal(k3, (16, 8)) * 0.1

    def tp(x, w1, w2):
        s = row_parallel_scatter(x, local_shard(w1, 0))
        return gather_column_parallel(s, local_shard(w2, 1))

    got = jax.jit(_compat.shard_map(
        tp, mesh=mesh,
        in_specs=(P(None, MODEL_AXIS), P(), P()),
        out_specs=P(None, MODEL_AXIS), check_vma=False))(x, w1, w2)
    want = (x @ w1) @ w2
    assert jnp.max(jnp.abs(got - want)) < TOL


@pytest.mark.parametrize("chunks", [2, 4])
def test_fused_scatter_gather_pair_bitwise_vs_unfused(chunks):
    mesh = _mesh()
    key = jax.random.PRNGKey(9)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (16, 16))
    w1 = jax.random.normal(k2, (16, 16)) * 0.1
    w2 = jax.random.normal(k3, (16, 8)) * 0.1

    def run(fuse, n=None):
        def tp(x, w1, w2):
            s = row_parallel_scatter(x, local_shard(w1, 0), fuse=fuse,
                                     fuse_chunks=n)
            return gather_column_parallel(s, local_shard(w2, 1),
                                          fuse=fuse, fuse_chunks=n)

        got = jax.jit(_compat.shard_map(
            tp, mesh=mesh, in_specs=(P(None, MODEL_AXIS), P(), P()),
            out_specs=P(None, MODEL_AXIS), check_vma=False))(x, w1, w2)
        import numpy as np
        return np.asarray(got).tobytes()

    assert run(True, chunks) == run(False)


def test_tp_gradients_match_dense():
    mesh = _mesh(2)
    key = jax.random.PRNGKey(4)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (4, 8))
    w1 = jax.random.normal(k2, (8, 16)) * 0.1
    w2 = jax.random.normal(k3, (16, 8)) * 0.1

    sm = _compat.shard_map(
        lambda x, w1, w2: tp_mlp(x, local_shard(w1, 1), None,
                                 local_shard(w2, 0), None),
        mesh=mesh, in_specs=(P(),) * 3, out_specs=P(), check_vma=False)
    got = jax.jit(jax.grad(lambda w1, w2: jnp.sum(sm(x, w1, w2) ** 2),
                           (0, 1)))(w1, w2)
    want = jax.grad(
        lambda w1, w2: jnp.sum((jax.nn.gelu(x @ w1) @ w2) ** 2),
        (0, 1))(w1, w2)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4
