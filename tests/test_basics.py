"""Process-info API tests (≙ reference test/test_common.py:26-74, which
checks hvd.rank()/size() against the launcher's env vars; here topology
comes from the JAX device enumeration)."""

import jax
import numpy as np
import pytest


def test_size_and_ranks(hvd):
    assert hvd.size() == len(jax.devices())
    assert hvd.local_size() == len(jax.devices())
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.process_index() == 0
    assert hvd.process_count() == 1


def test_mpi_threads_supported(hvd):
    assert hvd.mpi_threads_supported() is True


def test_subset_init(hvd2):
    assert hvd2.size() == 2


def test_not_initialized_raises():
    import horovod_tpu as hvd

    if hvd.is_initialized():
        hvd.shutdown()
    with pytest.raises(hvd.NotInitializedError):
        hvd.size()
    with pytest.raises(hvd.NotInitializedError):
        hvd.allreduce(np.ones(3))


def test_reinit_is_idempotent(hvd):
    n = hvd.size()
    hvd.init()
    assert hvd.size() == n


def test_mesh_axis(hvd):
    m = hvd.mesh()
    assert m.axis_names == (hvd.REPLICA_AXIS,)
    assert m.devices.size == hvd.size()
