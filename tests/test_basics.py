"""Process-info API tests (≙ reference test/test_common.py:26-74, which
checks hvd.rank()/size() against the launcher's env vars; here topology
comes from the JAX device enumeration)."""

import jax
import numpy as np
import pytest


def test_size_and_ranks(hvd):
    assert hvd.size() == len(jax.devices())
    assert hvd.local_size() == len(jax.devices())
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.process_index() == 0
    assert hvd.process_count() == 1


def test_mpi_threads_supported(hvd):
    assert hvd.mpi_threads_supported() is True


def test_subset_init(hvd2):
    assert hvd2.size() == 2


def test_not_initialized_raises():
    import horovod_tpu as hvd

    if hvd.is_initialized():
        hvd.shutdown()
    with pytest.raises(hvd.NotInitializedError):
        hvd.size()
    with pytest.raises(hvd.NotInitializedError):
        hvd.allreduce(np.ones(3))


def test_reinit_is_idempotent(hvd):
    n = hvd.size()
    hvd.init()
    assert hvd.size() == n


def test_mesh_axis(hvd):
    m = hvd.mesh()
    assert m.axis_names == (hvd.REPLICA_AXIS,)
    assert m.devices.size == hvd.size()


def test_start_stop_timeline_at_runtime(hvd, tmp_path):
    """Runtime timeline control (post-v0.13 hvd.start_timeline /
    stop_timeline; the reference only had the init-time env var): start
    mid-job, capture negotiation + execution events, stop (valid JSON),
    then start a SECOND file — switching works."""
    import json

    import jax.numpy as jnp

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    hvd.start_timeline(str(a))
    hvd.allreduce(jnp.ones((4,)), name="tl.op1", average=False)
    hvd.start_timeline(str(b))  # switch: closes a, records to b
    hvd.allgather(jnp.ones((2, 2)), name="tl.op2")
    hvd.stop_timeline()
    hvd.allreduce(jnp.ones((4,)), name="tl.op3", average=False)  # untraced

    def events(path):
        text = path.read_text()
        arr = json.loads(text if text.rstrip().endswith("]")
                         else text.rstrip().rstrip(",") + "]")
        return {e.get("name") for e in arr if isinstance(e, dict)}

    names_a = events(a)
    assert any("NEGOTIATE" in (n or "") for n in names_a)
    names_b = events(b)
    assert any("ALLGATHER" in (n or "") for n in names_b), names_b
    # op3 ran untraced: its name appears in neither file's process rows.
    all_rows = names_a | names_b
    assert "tl.op3" not in all_rows
