"""Guard the driver-facing bench artifact: `python bench.py --smoke` must
emit exactly one parseable JSON line with the contract fields, whatever
else happens (the driver records this output verbatim)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_emits_contract_json():
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        env=env, cwd=REPO, capture_output=True, timeout=560)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in payload, payload
    assert payload["value"] is not None and payload["value"] > 0
    # Round 4: the supervisor appends an eager/dynamic-path smoke result
    # (on the driver's TPU run this is the on-chip evidence; here CPU).
    assert payload.get("eager_tpu_smoke") == "ok", payload
    # Round 5: the attempt log rides along on success too.
    events = [e["event"] for e in payload["attempt_log"]]
    assert "probe_ok" in events and "measure_ok" in events, payload


def test_bench_control_mode_contract_and_speedup():
    """`--mode control` (round 6): the control-plane microbench emits
    one contract JSON line — no XLA, no tunnel, so it is fast enough
    for tier-1 — and the response cache must show a real speedup (the
    CI job gates at 2x; this asserts a loaded-machine-safe floor —
    a saturated single-core box has measured 1.26x in-suite against
    ~5x quiet, so the floor stays below that)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "control", "--control-seconds", "0.5"],
        env=dict(os.environ), cwd=REPO, capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "cache_on",
                "cache_off", "speedup"):
        assert key in payload, payload
    assert payload["metric"] == "control_plane_negotiations_per_sec"
    assert payload["cache_on"] > 0 and payload["cache_off"] > 0
    assert payload["speedup"] >= 1.2, payload
    # hvd-telemetry overhead A/B rides the JSON (ISSUE 4 gate): both
    # rates present, the pct computed, and the counters attached.  The
    # ok-boolean itself is asserted by CI on a quiet box, not here — a
    # loaded tier-1 machine can fake either direction.
    tel = payload["telemetry"]
    assert tel["cache_on_metrics_on"] > 0
    assert tel["cache_on_metrics_off"] > 0
    assert "overhead_pct" in tel and "overhead_ok" in tel
    assert isinstance(tel["counters"], dict)
    # hvd-trace overhead A/B rides the same JSON (ISSUE 10 gate, same
    # quiet-box caveat for the ok-boolean).
    tr = payload["trace"]
    assert tr["trace_on"] > 0 and tr["trace_off"] > 0
    assert "overhead_pct" in tr and "overhead_ok" in tr
    # Tree-overlay section (thousand-rank control plane): rank-0 rx
    # frames per simulated cycle must be structurally sub-linear —
    # one merged envelope per direct child, bounded by
    # fanout*log_fanout(world) — at every simulated world size.
    tree = payload["tree"]
    assert {w["world"] for w in tree["worlds"]} == {64, 256, 1024}
    for w in tree["worlds"]:
        assert w["tree_frames_per_cycle"] <= 2 * w["fanout_log_bound"]
        assert w["tree_frames_per_cycle"] * 4 \
            <= w["flat_frames_per_cycle"]
        assert w["negotiations_per_sec"] > 0


def test_bench_dataplane_mode_contract_and_gates():
    """`--mode dataplane` (this round): the data-plane microbench emits
    one contract JSON line — CPU-only like `--mode control`, so it is
    fast enough for tier-1 — and must clear the DETERMINISTIC gates:
    ≥ 2x dispatches/cycle reduction, bitwise identity, hierarchical ≡
    flat psum.  The throughput gate (`--check-speedup`) lives in the CI
    `dataplane-bench` job only: wall-clock ratios on a loaded shared
    box are noise (measured 3.9–10.6x quiet vs ~1x under a concurrent
    test run), and tier-1 must not flake on them."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "dataplane"],
        env=dict(os.environ), cwd=REPO, capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "eager_us",
                "megakernel_us", "speedup", "dispatches_per_cycle",
                "dispatch_reduction", "bitwise_identical",
                "hierarchical_equal"):
        assert key in payload, payload
    assert payload["metric"] == "dataplane_fused_cycle_latency_us"
    assert payload["dispatches_per_cycle"]["megakernel"] >= 1
    assert payload["dispatch_reduction"] >= 2.0, payload
    assert payload["bitwise_identical"] is True, payload
    assert payload["hierarchical_equal"] is True, payload
    # hvd-telemetry overhead A/B rides this JSON too (ISSUE 4): the
    # megakernel counters must show real launches were accounted.
    tel = payload["telemetry"]
    assert tel["megakernel_us_metrics_off"] > 0
    assert "overhead_pct" in tel
    assert tel["counters"].get("megakernel.launches", 0) >= 1, tel
    # hvd-trace overhead A/B on the same leg (ISSUE 10).
    tr = payload["trace"]
    assert tr["megakernel_us_trace_off"] > 0
    assert "overhead_pct" in tr and "overhead_ok" in tr
    # Bytes-on-wire accounting (ISSUE 6): per-compressor legs with
    # logical vs wire bytes per cycle, the compression ratio, the
    # eager-reference equality verdict, and the dispatch count proving
    # the quantize pipeline stayed inside the one fused executable.
    # Deterministic gates only — the throughput floor lives in CI.
    compression = payload["compression"]
    for codec in ("none", "int8", "int4"):
        leg = compression[codec]
        for key in ("cycle_us", "speedup_vs_uncompressed",
                    "dispatches_per_cycle", "logical_bytes_per_cycle",
                    "wire_bytes_per_cycle", "compression_ratio",
                    "reference_equal"):
            assert key in leg, (codec, leg)
        assert leg["dispatches_per_cycle"] == 1, (codec, leg)
    assert compression["none"]["compression_ratio"] == 1.0
    assert compression["int8"]["compression_ratio"] >= 3.0, compression
    assert compression["int4"]["compression_ratio"] >= 6.0, compression
    assert compression["int8"]["reference_equal"] is True, compression
    assert compression["int4"]["reference_equal"] is True, compression
    assert compression["int8"]["wire_bytes_per_cycle"] \
        < compression["none"]["wire_bytes_per_cycle"]
    assert tel["counters"].get("compression.ratio", 0) >= 1.0, tel


def test_bench_fused_mode_contract_and_gates():
    """`--mode fused` (this round): the hvd-fuse microbench emits one
    contract JSON line — CPU-only like the other microbenches — and
    must clear the DETERMINISTIC gates: every fused program bitwise-
    identical to its unfused reference, exactly ONE XLA dispatch per
    fused group on both legs, and the HVD_TPU_FUSE=off fallback pinning
    the reference bytes.  The exposed-communication strictly-below gate
    is wall-clock (XLA:CPU thunk-runtime overlap under a loaded tier-1
    box is not guaranteed) — it lives in the CI `fused-bench` job; here
    only the measurement's presence and shape are asserted."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "fused"],
        env=dict(os.environ), cwd=REPO, capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "exposed_comm",
                "bitwise", "dispatches_per_fused_group", "chunks"):
        assert key in payload, payload
    assert payload["metric"] == "fused_exposed_comm_us"
    for name, ok in payload["bitwise"].items():
        assert ok is True, (name, payload["bitwise"])
    for leg, disp in payload["dispatches_per_fused_group"].items():
        assert disp == 1, (leg, payload["dispatches_per_fused_group"])
    ec = payload["exposed_comm"]
    for key in ("unfused_us", "fused_us", "hidden_pct",
                "strictly_below"):
        assert key in ec, ec
    assert ec["unfused_us"] >= 0 and ec["fused_us"] >= 0
    assert payload["chunks"] >= 1
    tel = payload["telemetry"]
    assert tel["groups_compiled"] >= 1 and tel["launches"] >= 1, tel


def test_bench_input_mode_contract_and_identity():
    """`--mode input` (this round): the input-pipeline microbench emits
    one contract JSON line — CPU-only like the other microbenches — and
    must clear the DETERMINISTIC gate: bitwise-identical trained params
    prefetch on vs off (overlap reorders host work, never arithmetic).
    The ≥ 1.3x throughput gate lives in the CI `input-bench` job; here
    only a loaded-box-safe floor is asserted (wall-clock ratios under a
    concurrent tier-1 run are noise)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "input"],
        env=dict(os.environ), cwd=REPO, capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "prefetch_on",
                "prefetch_off", "speedup", "params_identical",
                "loader_delay_ms"):
        assert key in payload, payload
    assert payload["metric"] == "input_pipeline_steps_per_sec"
    assert payload["prefetch_on"] > 0 and payload["prefetch_off"] > 0
    assert payload["params_identical"] is True, payload
    # Host-overlap must not LOSE throughput even on a loaded box.
    assert payload["speedup"] >= 0.9, payload
    tel = payload["telemetry"]
    assert tel["batches_staged"] and tel["batches_staged"] > 0


def test_bench_serving_mode_contract_and_determinism():
    """`--mode serving` (this round): the hvd-serve microbench emits one
    contract JSON line and must clear BOTH deterministic gates: the
    continuous and static schedulers produce identical completions
    (batch-composition invariance), and the engine rollout is bitwise-
    equal to the non-incremental forward.  The ≥ 1.5x tokens/sec gate
    lives in the CI `serving-bench` job; here only a loaded-box-safe
    floor is asserted.  Quick-size traces (the deterministic gates hold
    at any trace size); the CI job runs the full trace."""
    env = dict(os.environ)
    env["HVD_TPU_BENCH_SERVING_QUICK"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "serving"],
        env=env, cwd=REPO, capture_output=True, timeout=420)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "continuous",
                "static", "speedup", "results_identical",
                "bitwise_identical"):
        assert key in payload, payload
    assert payload["metric"] == "serving_tokens_per_sec"
    assert payload["results_identical"] is True, payload
    assert payload["bitwise_identical"] is True, payload
    for leg in ("continuous", "static"):
        assert payload[leg]["tokens_per_sec"] > 0
        assert payload[leg]["ttft_ms"]["p50"] > 0
        assert payload[leg]["token_ms"]["p99"] >= \
            payload[leg]["token_ms"]["p50"]
    # Both legs generate the same token count from the same trace.
    assert payload["continuous"]["tokens"] == payload["static"]["tokens"]
    # Continuous batching must not LOSE throughput even on a loaded box.
    assert payload["speedup"] >= 0.9, payload


@pytest.mark.slow
def test_bench_overlap_mode_contract_and_identity():
    """`--mode overlap` (this round): the backward/communication-overlap
    microbench emits one contract JSON line and must clear every
    bitwise gate — overlapped ≡ monolithic (streaming schedule),
    overlapped ≡ serialized (segmented schedule, incl. under int8 wire
    quantization: per-bucket EF residuals).  The throughput floor lives
    in the CI `overlap-bench` job; wall-clock ratios under a concurrent
    tier-1 run are noise, so none is asserted here (the overlap win
    needs a real accelerator mesh — on the CPU mesh the two legs do the
    same work on one shared thread pool).  Quick-size like the pipeline
    test: the bitwise gates hold at any chain size and compile time
    dominates the full-size run; the CI `overlap-bench` job runs full.
    Slow-marked: even quick-size, XLA compile of the schedule variants
    is ~100 s on a 1-core box — the tier-1 time budget can't carry it,
    and both the CI `full` leg and the `overlap-bench` job still run
    every gate."""
    env = dict(os.environ)
    env["HVD_TPU_BENCH_OVERLAP_QUICK"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "overlap"],
        env=env, cwd=REPO, capture_output=True, timeout=540)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "overlapped",
                "serialized", "monolithic", "speedup",
                "bitwise_identical", "serial_identical",
                "segmented_close", "int8", "buckets", "segments"):
        assert key in payload, payload
    assert payload["metric"] == "overlap_steps_per_sec"
    assert payload["overlapped"] > 0 and payload["serialized"] > 0 \
        and payload["monolithic"] > 0
    assert payload["bitwise_identical"] is True, payload
    assert payload["serial_identical"] is True, payload
    assert payload["segmented_close"] is True, payload
    assert payload["int8"]["bitwise_identical"] is True, payload
    assert payload["int8"]["quantized_active"] is True, payload
    # The np=2 mp leg rides the JSON; 'unavailable' is legitimate on a
    # jax without np>1 CPU collectives (this container), 'failed' is a
    # real regression.
    assert payload["mp"]["status"] in ("ok", "unavailable", "skipped"), \
        payload["mp"]
    # The transformer chain really segmented and streamed per bucket.
    assert payload["segments"] > 1 and payload["buckets"] > payload["segments"]
    tel = payload["telemetry"]
    assert tel["buckets_dispatched"] and tel["buckets_dispatched"] > 0
    assert tel["fallbacks"] == 0, payload


def test_bench_pipeline_mode_contract_and_identity():
    """`--mode pipeline` (this round): the 1F1B MPMD pipeline-schedule
    microbench emits one contract JSON line and must clear the
    deterministic gates — 1f1b params/loss bitwise ≡ the GPipe-ordered
    dispatch of the same per-stage executables, allclose vs the
    monolithic microbatch-mean gradient, and the exposed-bubble
    seconds strictly below the gpipe leg (the gpipe leg pays fence +
    serialized dispatch + reduction inside the measured window, so the
    ordering survives a loaded box).  The steps/sec floor lives in the
    CI `pipeline-bench` job."""
    env = dict(os.environ)
    env["HVD_TPU_BENCH_PIPELINE_QUICK"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "pipeline"],
        env=env, cwd=REPO, capture_output=True, timeout=540)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "schedule_1f1b", "schedule_gpipe", "speedup",
                "bitwise_identical", "reference_close",
                "exposed_bubble_seconds_per_step", "bubble_hidden",
                "plan", "buckets"):
        assert key in payload, payload
    assert payload["metric"] == "pipeline_steps_per_sec"
    assert payload["schedule_1f1b"] > 0 and payload["schedule_gpipe"] > 0
    assert payload["bitwise_identical"] is True, payload
    assert payload["reference_close"] is True, payload
    assert payload["bubble_hidden"] is True, payload
    plan = payload["plan"]
    # 1F1B's memory bound: peak in-flight activations below GPipe's.
    assert plan["peak_activations_1f1b"] < plan["peak_activations_gpipe"]
    assert payload["buckets"] >= plan["n_stages"]


def test_bench_memory_mode_contract_and_gates():
    """`--mode memory` (this round): the hvd-mem microbench emits one
    contract JSON line and must clear its deterministic gates — the
    planner's framework-bytes prediction within ±15 % of the measured
    ledger high-watermark on both legs, byte-identical plans for
    identical configs, and the seeded RESOURCE_EXHAUSTED producing a
    forensic dump naming the executable and ≥3 ledger categories."""
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "memory", "--check-memory-plan", "15"],
        env=env, cwd=REPO, capture_output=True, timeout=540)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "dataplane", "pipeline",
                "plan_deterministic", "oom_dump",
                "ledger_overhead_pct"):
        assert key in payload, payload
    assert payload["metric"] == "memory_plan_prediction_error_pct"
    for leg in ("dataplane", "pipeline"):
        err = payload[leg]["prediction_error_pct"]
        assert err is not None and err <= 15.0, payload
    assert payload["plan_deterministic"] is True
    oom = payload["oom_dump"]
    assert oom["ok"] is True and oom["executable"], payload
    assert len(oom["top_categories"]) >= 3, payload


def test_bench_routing_mode_contract_and_gates():
    """`--mode routing` (this round): the hvd-route microbench is pure
    Python (router + autoscaler + queueing sim — no XLA, no tunnel), so
    the full smoke trace with every --check-speedup gate armed fits
    tier-1: least-loaded+affinity beats round-robin on p99 TTFT AND
    tokens/sec, the failover leg's merged completions are
    digest-identical to the single-replica reference, and the
    autoscale leg boots/seeds/vetoes/drains planner-priced."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "routing", "--smoke", "--check-speedup", "1.3"],
        env=dict(os.environ), cwd=REPO, capture_output=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline",
                "round_robin", "affinity", "p99_ttft_speedup",
                "tokens_per_sec_speedup", "affinity_hit_rate",
                "deterministic_replay", "failover", "autoscale"):
        assert key in payload, payload
    assert payload["metric"] == "routing_tokens_per_sec"
    assert payload["value"] > 0
    # The gates themselves ran inside the subprocess (exit 0 above);
    # re-assert the headline ones on the parsed payload.
    assert payload["p99_ttft_speedup"] >= 1.3, payload
    assert payload["tokens_per_sec_speedup"] >= 1.3, payload
    assert payload["affinity_hit_rate"] > 0, payload
    assert payload["deterministic_replay"] is True
    assert payload["failover"]["digest_identical"] is True
    assert payload["failover"]["continuations"] >= 1
    assert payload["autoscale"]["scaled_up"] is True
    assert payload["autoscale"]["veto"] is True
    assert payload["autoscale"]["oom_free"] is True
    # Both policies place the same trace: same request count, different
    # placements (the digest distinguishes them).
    assert payload["round_robin"]["placement_digest"] != \
        payload["affinity"]["placement_digest"]


@pytest.mark.slow
def test_bench_failure_still_emits_contract_json():
    """A dead backend: the probe retries with backoff inside the budget
    (round-5 hardening), then fails with the structured JSON including
    the per-probe attempt log."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "bogus"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--attempts", "1", "--total-budget", "480"],
        env=env, cwd=REPO, capture_output=True, timeout=420)
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    payload = json.loads(lines[-1])
    assert payload["value"] is None
    assert "error" in payload
    # The CPU-only microbench sections ride the failure JSON too —
    # a dead tunnel can zero none of them (incl. this round's
    # memory section).
    assert "pipeline" in payload and "overlap" in payload, payload
    assert "memory" in payload, payload
    # The probe must have retried (>1 probe event) before giving up.
    probe_events = [e for e in payload["attempt_log"]
                    if e["event"] == "probe_fail"]
    assert len(probe_events) >= 2, payload["attempt_log"]


@pytest.mark.slow
def test_bench_budget_floor_still_emits_contract_json():
    """Even a near-zero total budget yields the one-line JSON contract
    (the probe gets a 10 s floor; on CPU it finishes inside it)."""
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--attempts", "1", "--total-budget", "40"],
        env=env, cwd=REPO, capture_output=True, timeout=360)
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert lines, proc.stdout.decode() + proc.stderr.decode()[-2000:]
    payload = json.loads(lines[-1])
    assert "metric" in payload and "value" in payload
