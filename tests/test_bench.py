"""Guard the driver-facing bench artifact: `python bench.py --smoke` must
emit exactly one parseable JSON line with the contract fields, whatever
else happens (the driver records this output verbatim)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_emits_contract_json():
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        env=env, cwd=REPO, capture_output=True, timeout=420)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout.decode()
    payload = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in payload, payload
    assert payload["value"] is not None and payload["value"] > 0


@pytest.mark.slow
def test_bench_failure_still_emits_contract_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "bogus"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--attempts", "1"],
        env=env, cwd=REPO, capture_output=True, timeout=180)
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    payload = json.loads(lines[-1])
    assert payload["value"] is None
    assert "error" in payload
