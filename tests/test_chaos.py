"""hvd-chaos (ISSUE 9): fault-spec grammar + deterministic replay, the
shared backoff policy, the transport session-resume protocol (replay
rings, reconnect, grace, frame deadlines), checkpoint-writer retries,
the serving client-disconnect abort path, and the scenario matrix's
shape — with the satellite assertions that the flight-recorder dumps'
tails NAME each injected fault class."""

import glob
import json
import os
import random
import socket
import struct
import threading
import time

import pytest

import horovod_tpu.chaos as chaos
from horovod_tpu.chaos import spec as chaos_spec
from horovod_tpu.utils.retry import BackoffPolicy, retry_call

THRESHOLD = 1 << 20


@pytest.fixture()
def chaos_env(monkeypatch):
    """Arm/disarm HVD_TPU_FAULTS around a test and always restore the
    unarmed module state afterwards."""

    def arm(spec_text):
        monkeypatch.setenv("HVD_TPU_FAULTS", spec_text)
        return chaos.reload()

    yield arm
    monkeypatch.delenv("HVD_TPU_FAULTS", raising=False)
    chaos.reload()


# ---------------------------------------------------------------------------
# Grammar + determinism (the replay contract)
# ---------------------------------------------------------------------------

def test_parse_grammar_clauses_keys_and_seed():
    s = chaos_spec.parse(
        "transport.reset:count=2:after=5:rank=1;"
        "ckpt.oserror:p=0.5;input.stall:delay=0.25@99")
    assert s.seed == 99
    assert s.sites() == ["ckpt.oserror", "input.stall",
                         "transport.reset"]
    assert "transport.reset:count=2:after=5:rank=1" in s.describe()
    assert s.describe().endswith("@99")


def test_parse_defaults_bare_clause_fires_once():
    s = chaos_spec.parse("transport.drop")
    assert s.fire("transport.drop") is not None
    assert s.fire("transport.drop") is None  # count defaulted to 1


@pytest.mark.parametrize("bad,fragment", [
    ("transport.explode", "valid sites"),
    ("transport.drop:zap=1", "valid keys"),
    ("transport.drop:count=x", "bad value"),
    ("transport.drop@notanint", "seed"),
    ("transport.drop:p=1.5", "bad value"),
])
def test_parse_errors_name_the_problem(bad, fragment):
    with pytest.raises(ValueError, match=fragment):
        chaos_spec.parse(bad)


def test_validate_env_rejects_typos(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULTS", "transprot.reset@1")
    with pytest.raises(ValueError, match="valid sites"):
        chaos.validate_env()


def test_same_spec_and_seed_identical_fault_sequence():
    """The replay acceptance criterion: same spec + seed ⇒ the
    identical fault sequence, decision by decision."""
    text = "transport.drop:p=0.3:count=50@1234"
    a, b = chaos_spec.parse(text), chaos_spec.parse(text)
    seq_a = [a.fire("transport.drop") is not None for _ in range(400)]
    seq_b = [b.fire("transport.drop") is not None for _ in range(400)]
    assert seq_a == seq_b
    assert any(seq_a)  # and it does fire
    # A different seed yields a different sequence (p-decisions are
    # seed-dependent, not wall-clock-dependent).
    c = chaos_spec.parse("transport.drop:p=0.3:count=50@77")
    seq_c = [c.fire("transport.drop") is not None for _ in range(400)]
    assert seq_a != seq_c


def test_count_after_and_rank_filters():
    s = chaos_spec.parse("transport.reset:count=2:after=3:rank=1@0")
    # rank mismatch: never fires, opportunities still counted.
    assert all(s.fire("transport.reset", rank=0) is None
               for _ in range(10))
    assert s.opportunities("transport.reset") == 10
    s = chaos_spec.parse("transport.reset:count=2:after=3:rank=1@0")
    fired = [s.fire("transport.reset", rank=1) is not None
             for _ in range(10)]
    assert fired == [False] * 3 + [True, True] + [False] * 5


def test_maybe_reorder_is_deterministic(chaos_env):
    chaos_env("coord.reorder:count=1@5")
    assert chaos.maybe_reorder("coord.reorder", [1, 2, 3]) == [3, 2, 1]
    assert chaos.maybe_reorder("coord.reorder", [1, 2, 3]) == [1, 2, 3]


def test_unarmed_fire_is_none(chaos_env):
    chaos.reload()
    assert chaos.fire("transport.drop") is None
    assert not chaos.active()


# ---------------------------------------------------------------------------
# Shared backoff policy (utils/retry.py)
# ---------------------------------------------------------------------------

def test_backoff_policy_jitter_bounds_and_cap():
    p = BackoffPolicy(base=0.1, cap=1.0, rng=random.Random(7))
    for k in range(12):
        d = p.delay(k)
        assert 0.0 <= d <= min(1.0, 0.1 * 2 ** k)
    # The ceiling grows then saturates at the cap.
    ceilings = [min(1.0, 0.1 * 2 ** k) for k in range(12)]
    assert ceilings[-1] == 1.0


def test_backoff_policy_rejects_nonsense():
    with pytest.raises(ValueError):
        BackoffPolicy(base=0.0)
    with pytest.raises(ValueError):
        BackoffPolicy(base=1.0, cap=0.5)


def test_retry_call_retries_then_succeeds_and_reports():
    calls = {"n": 0}
    seen = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(28, "flaky")
        return "ok"

    out = retry_call(flaky, attempts=4,
                     policy=BackoffPolicy(base=0.001, cap=0.002),
                     on_retry=lambda a, e, d: seen.append((a, str(e))))
    assert out == "ok" and calls["n"] == 3
    assert [a for a, _ in seen] == [0, 1]


def test_retry_call_exhaustion_reraises_original():
    with pytest.raises(OSError, match="always"):
        retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                   attempts=3,
                   policy=BackoffPolicy(base=0.001, cap=0.002))


# ---------------------------------------------------------------------------
# Replay ring (ops/transport.py)
# ---------------------------------------------------------------------------

def test_frame_ring_since_and_overflow():
    from horovod_tpu.ops.transport import _FrameRing

    r = _FrameRing(limit=4)
    for i in range(6):
        r.append(8, bytes([i]))
    assert r.count == 6
    # The peer received 3 frames: frames 3..5 are the missing suffix.
    assert [p for _, p in r.since(3)] == [b"\x03", b"\x04", b"\x05"]
    assert r.since(6) == []          # fully caught up
    assert r.since(1) is None        # gap beyond the ring: unplayable
    assert r.since(7) is None        # claims more than ever sent


# ---------------------------------------------------------------------------
# Transport session resume over real sockets (no XLA)
# ---------------------------------------------------------------------------

@pytest.fixture()
def cp_pair():
    """A controller + worker transport pair over loopback with live
    response-cache replicas — the test_cache two-rank harness, kept as
    a fixture so every reconnect test reuses one teardown path."""
    from horovod_tpu.ops import cache as hvd_cache
    from horovod_tpu.ops import transport as T
    from horovod_tpu.ops.coordinator import Coordinator

    if os.environ.get("HVD_TPU_NO_SOCKETS") == "1":
        pytest.skip("sandbox without loopback sockets")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ctrl_cache = hvd_cache.ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD,
                        cache=ctrl_cache)
    holder = {}
    th = threading.Thread(
        target=lambda: holder.__setitem__(
            "ctrl", T.ControllerTransport(coord, 2, port)),
        daemon=True)
    th.start()
    time.sleep(0.1)
    worker = T.WorkerTransport("127.0.0.1", port, 1)
    th.join(timeout=10.0)
    ctrl = holder["ctrl"]
    ctrl.cache = ctrl_cache
    worker.cache = hvd_cache.ResponseCache(rank=1)
    yield ctrl, worker, coord, ctrl_cache
    worker.close()
    ctrl.close()
    coord.close()


def _cp_request(rank, name):
    from horovod_tpu.ops import wire
    from horovod_tpu.ops.wire import Request

    return Request(rank, wire.RequestType.ALLREDUCE,
                   wire.DataType.FLOAT32, name, -1, -1, (4,),
                   wire.ReduceOp.SUM, 0, ())


def _controller_tick(ctrl, coord, cache):
    ctrl.expire_grace()
    ctrl.flush_unrouted()
    marker = cache.take_flush_marker()
    replayed, groups, epoch, compact = cache.take_ready(
        lambda psid: THRESHOLD)
    negotiated = coord.poll_responses({})
    resps = (([marker] if marker is not None else [])
             + replayed + negotiated)
    n_other = (1 if marker is not None else 0) + len(negotiated)
    if resps:
        if compact and groups and n_other == 0:
            ctrl.broadcast_replay(groups, epoch)
        else:
            ctrl.broadcast_responses(resps)
    rid = frozenset(id(r) for r in replayed)
    for r in resps:
        cache.observe_response(r, replay=id(r) in rid)
    return resps


def _run_cycle(ctrl, worker, coord, cache, names=("x", "y"),
               deadline=10.0):
    """One full negotiation cycle over the wire; returns the worker's
    received responses.  Tolerates a mid-cycle reconnect (that is the
    point)."""
    from horovod_tpu.ops.wire import ResponseType

    wreqs = {}
    for n in names:
        req = _cp_request(1, n)
        wreqs[n] = req
        worker.submit(req)
    worker.flush_requests()
    for n in names:
        ctrl.submit(_cp_request(0, n))
    want = set(names)
    got = []
    end = time.monotonic() + deadline
    seen_ctrl = set()
    while time.monotonic() < end:
        for r in _controller_tick(ctrl, coord, cache):
            seen_ctrl.update(r.tensor_names)
        batch = worker.poll_responses()
        if batch is not None:
            for r in batch:
                assert r.response_type != ResponseType.SHUTDOWN, \
                    r.error_message
                wcache = worker.cache
                if wcache is not None:
                    wcache.observe_response(r, own_requests={1: wreqs})
                got.append(r)
        if want <= {n for r in got for n in r.tensor_names} \
                and want <= seen_ctrl:
            return got
        time.sleep(0.005)
    raise AssertionError(
        f"cycle never completed: worker got "
        f"{[r.tensor_names for r in got]}, controller saw {seen_ctrl}")


def test_reconnect_resumes_session_with_ring_replay(cp_pair, tmp_path,
                                                    monkeypatch,
                                                    capfd):
    """The tentpole wire contract: a hard connection reset mid-steady-
    state is absorbed by reconnect + replay-ring resume; the cache
    replica stays attached and later cycles still complete; the flight
    dump's tail names the reconnect (satellite)."""
    import horovod_tpu.telemetry as tel
    from horovod_tpu.ops import transport as T

    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    ctrl, worker, coord, cache = cp_pair
    _run_cycle(ctrl, worker, coord, cache)          # cold
    _run_cycle(ctrl, worker, coord, cache)          # steady (compact)
    before = tel.metrics().get("transport.reconnects",
                               {}).get("value", 0)
    T._hard_close(worker._sock)                     # the fault
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        _controller_tick(ctrl, coord, cache)  # serve the reconnect era
        now = tel.metrics().get("transport.reconnects",
                                {}).get("value", 0)
        if now > before:
            break
        time.sleep(0.02)
    else:
        raise AssertionError("worker never reconnected")
    assert worker.cache is not None          # replica resumed, not dropped
    _run_cycle(ctrl, worker, coord, cache)          # post-resume cycle
    err = capfd.readouterr().err
    assert "session resumed" in err
    # Satellite: the dump exists and its tail names the fault class.
    dumps = sorted(glob.glob(str(tmp_path / "*reconnect*.json")))
    assert dumps, sorted(glob.glob(str(tmp_path / "*")))
    payload = json.loads(open(dumps[-1]).read())
    tail = payload["events"][-10:]
    assert any(e["kind"] == "reconnected" for e in tail), tail
    assert any(e["kind"] == "transport_fault"
               and "reconnect" in e["args"][0] for e in tail), tail


def test_reconnect_epoch_mismatch_resumes_cache_less(cp_pair, capfd):
    """The epoch-stamped handshake: a worker whose replica epoch no
    longer matches the disconnect-time epoch must resume CACHE-LESS
    (and the controller flushes so no compact frame strands it) —
    desync is impossible by construction, and cycles still
    complete."""
    import horovod_tpu.telemetry as tel
    from horovod_tpu.ops import transport as T

    ctrl, worker, coord, cache = cp_pair
    _run_cycle(ctrl, worker, coord, cache)
    _run_cycle(ctrl, worker, coord, cache)
    # Locally desync the worker's replica epoch (a flush rank 0 never
    # broadcast — the exact state the verdict must catch).
    worker.cache.flush("test-induced desync")
    before = tel.metrics().get("transport.reconnects",
                               {}).get("value", 0)
    T._hard_close(worker._sock)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        _controller_tick(ctrl, coord, cache)
        if tel.metrics().get("transport.reconnects",
                             {}).get("value", 0) > before:
            break
        time.sleep(0.02)
    else:
        raise AssertionError("worker never reconnected")
    assert worker.cache is None              # dropped, not desynced
    _run_cycle(ctrl, worker, coord, cache)   # full-response broadcasts
    err = capfd.readouterr().err
    assert "resuming cache-less" in err
    assert "cache epoch" in err


def test_frame_deadline_names_peer_and_frame_type(monkeypatch, capfd):
    """Satellite: frame-level read deadlines produce a diagnostic
    naming the peer and the frame type, never a hang."""
    monkeypatch.setenv("HVD_TPU_FRAME_TIMEOUT", "0.4")
    from horovod_tpu.ops import cache as hvd_cache
    from horovod_tpu.ops import transport as T
    from horovod_tpu.ops.coordinator import Coordinator
    from horovod_tpu.telemetry import flight

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD)
    holder = {}
    th = threading.Thread(
        target=lambda: holder.__setitem__(
            "ctrl", T.ControllerTransport(coord, 2, port)),
        daemon=True)
    th.start()
    time.sleep(0.1)
    worker = T.WorkerTransport("127.0.0.1", port, 1)
    th.join(timeout=10.0)
    ctrl = holder["ctrl"]
    try:
        # A REQUEST_BATCH header promising 100 bytes, then silence:
        # the controller's mid-frame deadline must fire.
        worker._sock.sendall(struct.pack("<IB", 100, 8) + b"xx")
        deadline = time.monotonic() + 5.0
        event = None
        while time.monotonic() < deadline:
            event = next((e for e in flight.snapshot()
                          if e[1] == "frame_timeout"), None)
            if event is not None:
                break
            time.sleep(0.05)
        assert event is not None, "frame deadline never fired"
        # The diagnostic names the peer and the frame type (the flight
        # record carries the same fields as the printed warning).
        assert "rank 1" in str(event)
        assert "REQUEST_BATCH" in str(event)
        # The printed warning: poll-accumulate the capture — the print
        # races the flight record, and a block-buffered stderr under fd
        # capture can land the line's head in an earlier flush window,
        # so match on the event-specific tail.
        err = ""
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline \
                and "REQUEST_BATCH, 2/100 bytes" not in err:
            err += capfd.readouterr().err
            time.sleep(0.05)
        assert "REQUEST_BATCH, 2/100 bytes" in err
    finally:
        worker.close()
        ctrl.close()
        coord.close()


def test_truncated_frame_is_named(cp_pair, capfd):
    """Satellite: a frame cut off mid-wire is recorded as a truncated
    frame naming the peer and frame type (the reconnect machinery then
    recovers it — covered above)."""
    from horovod_tpu.telemetry import flight

    ctrl, worker, coord, cache = cp_pair
    _run_cycle(ctrl, worker, coord, cache)
    # Promise 64 payload bytes, deliver 3, then reset the socket.
    worker._sock.sendall(struct.pack("<IB", 64, 8) + b"abc")
    from horovod_tpu.ops import transport as T

    T._hard_close(worker._sock)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if any(e[1] == "truncated_frame" for e in flight.snapshot()):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("truncation never recorded")
    err = capfd.readouterr().err
    assert "truncated control frame" in err
    assert "REQUEST_BATCH" in err


def test_reconnect_exhaustion_poisons_with_named_diagnostic(
        cp_pair, monkeypatch, capfd):
    """The bounded end of the no-hang contract on the worker side: a
    controller that never comes back exhausts the reconnect deadline
    and pending ops fail with a diagnostic naming the fault."""
    from horovod_tpu.ops import transport as T
    from horovod_tpu.ops.wire import ResponseType

    monkeypatch.setenv("HVD_TPU_RECONNECT_DEADLINE", "1.0")
    # The poison path disarms jax.distributed's exit barrier — a
    # process-global latch this harness (which never initialized
    # jax.distributed) must re-arm for later in-process hvd.init()s.
    from horovod_tpu.core import cluster as _cluster

    monkeypatch.setattr(_cluster, "_disarmed", _cluster._disarmed)
    ctrl, worker, coord, cache = cp_pair
    _run_cycle(ctrl, worker, coord, cache)
    ctrl.close()  # the controller is gone for good
    T._hard_close(worker._sock)
    deadline = time.monotonic() + 15.0
    got = None
    while time.monotonic() < deadline:
        resps = worker.poll_responses()
        if resps and any(r.response_type == ResponseType.SHUTDOWN
                         for r in resps):
            got = [r for r in resps
                   if r.response_type == ResponseType.SHUTDOWN][0]
            break
        time.sleep(0.02)
    assert got is not None, "worker never poisoned its pending ops"
    assert "no reconnect within" in got.error_message, got.error_message


def test_grace_expiry_declares_rank_lost_with_reason(cp_pair,
                                                     monkeypatch):
    """Controller side of the bounded contract: a disconnected rank
    that never resumes becomes a lost rank once the grace window
    expires, with a reason naming the fault."""
    monkeypatch.setenv("HVD_TPU_RECONNECT_GRACE", "0.3")
    ctrl, worker, coord, cache = cp_pair
    _run_cycle(ctrl, worker, coord, cache)
    worker.close()  # no SHUTDOWN frame, no reconnect ever
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        ctrl.expire_grace()
        if ctrl.lost_ranks:
            break
        time.sleep(0.05)
    assert ctrl.lost_ranks == {1}
    assert "no reconnect within" in ctrl.lost_reasons[1]


def test_connect_backoff_logs_attempts_with_remaining_deadline(capfd):
    """Satellite: the initial connect loop uses the shared jittered
    backoff and logs every attempt with the remaining deadline."""
    from horovod_tpu.ops import transport as T

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="could not reach"):
        T.WorkerTransport("127.0.0.1", port, 3, connect_timeout=0.7)
    assert time.monotonic() - t0 < 10.0
    err = capfd.readouterr().err
    assert "[hvd-connect] rank 3" in err
    assert "before deadline" in err


# ---------------------------------------------------------------------------
# Checkpoint writer retries (utils/checkpoint.py)
# ---------------------------------------------------------------------------

def test_checkpoint_transient_oserror_retries_then_lands(
        chaos_env, tmp_path, capfd):
    import numpy as np

    import horovod_tpu.telemetry as tel
    from horovod_tpu.utils import checkpoint as ckpt

    chaos_env("ckpt.oserror:count=2@3")
    before = tel.metrics().get("checkpoint.retries",
                               {}).get("value", 0)
    tree = {"w": np.arange(16, dtype=np.float32)}
    handle = ckpt.write_tree_async(str(tmp_path / "m.msgpack"), tree,
                                   step=4)
    assert handle.wait(timeout=30.0)
    assert (tmp_path / "m.msgpack").exists()
    assert (tmp_path / "m.msgpack.step").read_text() == "4"
    after = tel.metrics().get("checkpoint.retries",
                              {}).get("value", 0)
    assert after - before >= 2
    assert "retrying" in capfd.readouterr().err
    # Atomicity held: no stranded tmp files.
    assert not glob.glob(str(tmp_path / "*.tmp.*"))


def test_checkpoint_retry_exhaustion_dump_names_fault(
        chaos_env, tmp_path, monkeypatch):
    """Satellite: retry exhaustion raises CheckpointError naming the
    injected fault, and the flight dump's tail records the retries and
    the final error."""
    import numpy as np

    from horovod_tpu.utils import checkpoint as ckpt

    monkeypatch.setenv("HVD_TPU_CKPT_RETRIES", "2")
    flight_dir = tmp_path / "flight"
    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(flight_dir))
    chaos_env("ckpt.oserror:count=9@4")
    tree = {"w": np.arange(8, dtype=np.float32)}
    handle = ckpt.write_tree_async(str(tmp_path / "m.msgpack"), tree)
    with pytest.raises(ckpt.CheckpointError, match="ckpt.oserror"):
        handle.wait(timeout=30.0)
    dumps = sorted(glob.glob(str(flight_dir / "*checkpoint-error*")))
    assert dumps, sorted(glob.glob(str(flight_dir / "*")))
    payload = json.loads(open(dumps[-1]).read())
    tail = payload["events"][-10:]
    assert any(e["kind"] == "ckpt_retry" for e in tail), tail
    assert any(e["kind"] == "checkpoint_error"
               and any("ckpt.oserror" in str(a) for a in e["args"])
               for e in tail), tail


# ---------------------------------------------------------------------------
# Prefetch stall injection (parallel/input.py)
# ---------------------------------------------------------------------------

def test_input_stall_injection_preserves_order_and_values(chaos_env,
                                                          hvd):
    import numpy as np

    chaos_env("input.stall:count=2:delay=0.1@6")
    batches = [np.full((8, 2), float(i), np.float32) for i in range(6)]
    out = [np.asarray(b)[0, 0] for b in
           hvd.prefetch_to_device(iter(batches))]
    assert out == [float(i) for i in range(6)]
    assert chaos.schedule().opportunities("input.stall") >= 6


# ---------------------------------------------------------------------------
# Serving: scheduler cancel + client-disconnect abort path
# ---------------------------------------------------------------------------

def test_scheduler_cancel_queued_finishes_immediately():
    from horovod_tpu.serving import (ContinuousBatchingScheduler,
                                     FinishReason, Request)

    s = ContinuousBatchingScheduler(max_slots=1, capacity=32)
    r1 = s.submit(Request(prompt=[1, 2], max_new_tokens=4))
    r2 = s.submit(Request(prompt=[3, 4], max_new_tokens=4))
    s.admit()
    assert s.cancel(r2, FinishReason.CLIENT_DISCONNECT) == "queued"
    assert r2.done.is_set()
    assert r2.finish_reason == FinishReason.CLIENT_DISCONNECT
    assert s.queue_depth() == 0
    # Active request: marked, evicted at the loop boundary.
    assert s.cancel(r1, FinishReason.CLIENT_DISCONNECT) == "active"
    assert not r1.done.is_set()
    assert s.evict_cancelled() == [0]
    assert r1.done.is_set()
    assert s.occupancy() == 0
    assert s.cancel(r1, FinishReason.CLIENT_DISCONNECT) == "gone"


def test_client_probe_detects_closed_socket():
    from horovod_tpu.telemetry.exporter import ClientProbe

    a, b = socket.socketpair()
    probe = ClientProbe(a)
    assert not probe.disconnected()
    b.close()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and not probe.disconnected():
        time.sleep(0.01)
    assert probe.disconnected()
    a.close()


def test_route_registry_pass_client_flag():
    from horovod_tpu.telemetry.exporter import RouteRegistry

    reg = RouteRegistry()
    reg.register("/a", lambda q, b: (200, b"", "t"))
    reg.register("/b", lambda q, b, c: (200, b"", "t"),
                 methods=("POST",), pass_client=True)
    assert reg.lookup("GET", "/a")[1] is False
    assert reg.lookup("POST", "/b")[1] is True


# ---------------------------------------------------------------------------
# The matrix's shape (the CI gate's coverage contract)
# ---------------------------------------------------------------------------

def test_matrix_covers_every_injection_point():
    """ISSUE 9 acceptance: at least one matrix entry per injection
    point — transport, coordinator, checkpoint, prefetch, serving."""
    from horovod_tpu.chaos import matrix

    families = set()
    for s in matrix.SCENARIOS:
        assert s.expect in ("recover", "diagnostic", "complete"), s
        assert s.cap > 0
        for clause in filter(None, s.spec.rpartition("@")[0].split(";")):
            families.add(clause.split(":")[0].split(".")[0])
        if s.name == "grace_expiry":
            families.add("transport")  # the fault is the hard kill
        if s.name == "serving_storm":
            families.add("serving")    # the fault is the load
    assert {"transport", "coord", "ckpt", "input",
            "serving"} <= families, families
    # Every spec parses (a typo'd matrix entry must fail HERE, not in
    # CI's chaos job).
    for s in matrix.SCENARIOS:
        if s.spec:
            chaos_spec.parse(s.spec)


def test_matrix_digest_and_result_parsing():
    from horovod_tpu.chaos import matrix

    d1 = matrix._digest([(1, "a"), (0, "b")])
    d2 = matrix._digest([(0, "b"), (1, "a")])
    assert d1 == d2  # order-insensitive
    assert d1 != matrix._digest([(0, "b")])
    dg = matrix._digest([(0, "b")])  # the real 24-hex shape
    out = f"noise\nCHAOS_RESULT rank=1 n=3 digest={dg}\nmore"
    assert matrix._parse_results(out) == {1: f"n=3 digest={dg}"}
    # Interleaved-writer hardening: a log fragment glued onto the
    # digest token (observed: "[hvd-tree]" under tier-1 load) or
    # prefixed to the line must not corrupt the parse.
    out = (f"CHAOS_RESULT rank=0 n=3 digest={dg}[hvd-tree] adopting\n"
           f"[hvd-chaos] x CHAOS_RESULT rank=1 n=3 digest={dg}")
    assert matrix._parse_results(out) == {0: f"n=3 digest={dg}",
                                          1: f"n=3 digest={dg}"}


def test_matrix_smoke_one_cp_scenario():
    """End-to-end runner mechanics on the cheapest scenario: real
    subprocesses, wall-clock cap, diagnostic assertion."""
    from horovod_tpu.chaos import matrix

    report = matrix.run_scenario(matrix.find("grace_expiry"))
    assert report["status"] == "PASS", report
