"""Object collectives (ops/objects.py): pickle-over-collective contract."""

import numpy as np

import horovod_tpu as hvd_api


def test_allgather_object_roundtrip(hvd):
    obj = {"epoch": 3, "name": "run-a", "metrics": [1.0, 2.5]}
    out = hvd_api.allgather_object(obj)
    assert isinstance(out, list) and len(out) == hvd_api.size()
    for o in out:
        assert o == obj


def test_broadcast_object_returns_root_value(hvd):
    obj = {"resume_from_epoch": 7, "nested": {"lr": 0.1}}
    got = hvd_api.broadcast_object(obj, root_rank=0)
    assert got == obj
    # Non-root convention: obj=None still returns the root's object
    # (single-process mode: rank 0 IS the caller, so pass the value).
    got2 = hvd_api.broadcast_object({"x": np.arange(3)}, root_rank=0)
    np.testing.assert_array_equal(got2["x"], np.arange(3))


def test_object_apis_on_every_frontend(hvd):
    import horovod_tpu.frontends.keras as khvd
    import horovod_tpu.frontends.tensorflow as tfhvd
    import horovod_tpu.frontends.torch as thvd

    for mod in (thvd, tfhvd, khvd):
        assert mod.allgather_object is hvd_api.allgather_object
        assert mod.broadcast_object is hvd_api.broadcast_object
