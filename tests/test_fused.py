"""hvd-fuse unit tests: fused computation-collective kernels
(ops/fused.py).

The bitwise contract is the load-bearing one — every fused primitive
must reproduce its unfused reference program's bytes exactly (chunking
runs along reduction-free axes only; ``bench.py --mode fused`` re-gates
the same contract plus the exposed-communication measurement).  The
integration call sites have their own suites (test_tensor_parallel.py,
test_expert_parallel.py, test_pipeline_parallel.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core import compat as _compat
from horovod_tpu.core.topology import MODEL_AXIS, make_mesh
from horovod_tpu.memory import ledger as ledger_mod
from horovod_tpu.memory import planner
from horovod_tpu.ops import fused as F


def _mesh(n=4):
    return make_mesh(model=n, devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# Chunk planning
# ---------------------------------------------------------------------------

def test_plan_chunks_even_split():
    assert F.plan_chunks(16, 4) == ((0, 4), (4, 4), (8, 4), (12, 4))


def test_plan_chunks_remainder_spreads_over_leading_chunks():
    assert F.plan_chunks(10, 4) == ((0, 3), (3, 3), (6, 2), (8, 2))


def test_plan_chunks_clamps_to_min_chunk_rows():
    # 6 rows / 4 requested → only 3 chunks keep >= MIN_CHUNK_ROWS.
    assert F.plan_chunks(6, 4) == ((0, 2), (2, 2), (4, 2))
    # Fewer rows than 2*MIN_CHUNK_ROWS: degenerate single-chunk plan —
    # the unfused reference program (the PR-7 gemv trap guard).
    assert F.plan_chunks(3, 4) == ((0, 3),)
    assert F.plan_chunks(1, 8) == ((0, 1),)


def test_plan_chunks_covers_every_row_exactly_once():
    for rows in (2, 5, 7, 16, 33):
        for want in (1, 2, 3, 4, 8):
            plan = F.plan_chunks(rows, want)
            covered = [s for start, size in plan
                       for s in range(start, start + size)]
            assert covered == list(range(rows)), (rows, want, plan)
            assert all(size >= F.MIN_CHUNK_ROWS for _, size in plan) \
                or len(plan) == 1


def test_plan_chunks_rejects_nonpositive():
    with pytest.raises(ValueError):
        F.plan_chunks(8, 0)


# ---------------------------------------------------------------------------
# Env knobs
# ---------------------------------------------------------------------------

def test_fuse_mode_normalizes_aliases(monkeypatch):
    monkeypatch.setenv(F.FUSE_ENV, "1")
    assert F.fuse_mode() == "on"
    monkeypatch.setenv(F.FUSE_ENV, "0")
    assert F.fuse_mode() == "off"
    assert not F.enabled()
    monkeypatch.delenv(F.FUSE_ENV)
    assert F.fuse_mode() == "auto"
    assert F.enabled()  # auto means on: the transform is bitwise


def test_enabled_override_beats_env(monkeypatch):
    monkeypatch.setenv(F.FUSE_ENV, "off")
    assert F.enabled(True)
    monkeypatch.setenv(F.FUSE_ENV, "on")
    assert not F.enabled(False)


def test_validate_env_rejects_bad_mode(monkeypatch):
    monkeypatch.setenv(F.FUSE_ENV, "sideways")
    with pytest.raises(ValueError, match="HVD_TPU_FUSE"):
        F.validate_env()


@pytest.mark.parametrize("bad", ["zero", "0", "-2", "1.5"])
def test_validate_env_rejects_bad_chunks(monkeypatch, bad):
    monkeypatch.delenv(F.FUSE_ENV, raising=False)
    monkeypatch.setenv(F.CHUNKS_ENV, bad)
    with pytest.raises(ValueError, match="HVD_TPU_FUSE_CHUNKS"):
        F.validate_env()


def test_fuse_chunks_env(monkeypatch):
    monkeypatch.delenv(F.CHUNKS_ENV, raising=False)
    assert F.fuse_chunks() == F.DEFAULT_CHUNKS
    monkeypatch.setenv(F.CHUNKS_ENV, "7")
    assert F.fuse_chunks() == 7


def test_init_validates_fusion_knobs(monkeypatch):
    # The knob fails hvd.init(), not the first fused dispatch (the
    # validate_env chain in core/state.init).
    import horovod_tpu as hvd

    monkeypatch.setenv(F.FUSE_ENV, "sideways")
    with pytest.raises(ValueError, match="HVD_TPU_FUSE"):
        hvd.init(devices=jax.devices())
    monkeypatch.delenv(F.FUSE_ENV)


def test_fusion_knobs_ride_env_fingerprint():
    # Both knobs select the compiled SPMD program, so they must be in
    # the HELLO env fingerprint (fleet-uniformity check).
    from horovod_tpu.ops import compression as _compression

    assert F.FUSE_ENV in _compression._SPMD_ENV_KNOBS
    assert F.CHUNKS_ENV in _compression._SPMD_ENV_KNOBS


# ---------------------------------------------------------------------------
# chunked_map
# ---------------------------------------------------------------------------

def test_chunked_map_off_calls_fn_once_on_whole_array():
    calls = []

    def fn(x):
        calls.append(x.shape)
        return x * 2

    x = jnp.ones((16, 4))
    out = F.chunked_map(fn, x, chunks=4, fuse=False)
    assert calls == [(16, 4)]
    assert out.shape == (16, 4)


def test_chunked_map_degenerate_plan_is_reference_program():
    calls = []

    def fn(x):
        calls.append(x.shape)
        return x

    F.chunked_map(fn, jnp.ones((3, 4)), chunks=4, fuse=True)
    assert calls == [(3, 4)]  # < 2*MIN_CHUNK_ROWS rows: one chunk


def test_chunked_map_concatenates_chunks_in_order():
    x = jnp.arange(16.0).reshape(16, 1)
    out = F.chunked_map(lambda c: c + 100.0, x, chunks=4, fuse=True)
    assert np.asarray(out).tobytes() == np.asarray(x + 100.0).tobytes()


def test_chunked_map_respects_axis():
    x = jnp.arange(32.0).reshape(2, 16)
    out = F.chunked_map(lambda c: c * 3.0, x, axis=1, chunks=4,
                        fuse=True)
    assert np.asarray(out).tobytes() == np.asarray(x * 3.0).tobytes()


# ---------------------------------------------------------------------------
# Fused primitives: bitwise vs the unfused reference program
# ---------------------------------------------------------------------------

def _bitwise(mesh, fn_fused, fn_ref, *args):
    run = lambda fn: np.asarray(jax.jit(_compat.shard_map(
        fn, mesh=mesh, in_specs=tuple(P() for _ in args), out_specs=P(),
        check_vma=False))(*args)).tobytes()
    return run(fn_fused) == run(fn_ref)


@pytest.mark.parametrize("chunks", [2, 4])
def test_matmul_psum_bitwise(chunks):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    assert _bitwise(
        mesh,
        lambda x, w: F.matmul_psum(x, w, axis_name=MODEL_AXIS,
                                   chunks=chunks, fuse=True),
        lambda x, w: jax.lax.psum(
            jnp.dot(x, w, preferred_element_type=jnp.float32),
            MODEL_AXIS),
        x, w)


@pytest.mark.parametrize("chunks", [2, 4])
def test_matmul_reduce_scatter_bitwise(chunks):
    mesh = _mesh()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    assert _bitwise(
        mesh,
        lambda x, w: F.matmul_reduce_scatter(
            x, w, axis_name=MODEL_AXIS, chunks=chunks, fuse=True),
        lambda x, w: jax.lax.psum_scatter(
            jnp.dot(x, w, preferred_element_type=jnp.float32),
            MODEL_AXIS, scatter_dimension=1, tiled=True),
        x, w)


@pytest.mark.parametrize("chunks", [2, 4])
def test_all_gather_matmul_bitwise(chunks):
    mesh = _mesh()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((32, 8)).astype(np.float32))
    assert _bitwise(
        mesh,
        lambda x, w: F.all_gather_matmul(
            x, w, axis_name=MODEL_AXIS, chunks=chunks, fuse=True),
        lambda x, w: jnp.dot(
            jax.lax.all_gather(x, MODEL_AXIS, axis=1, tiled=True), w,
            preferred_element_type=jnp.float32),
        x, w)


# ---------------------------------------------------------------------------
# Host-side services: FusedProgram, manifest, ledger, telemetry
# ---------------------------------------------------------------------------

def test_fused_program_compiles_once_and_matches_jit():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    fn = jax.jit(_compat.shard_map(
        lambda x, w: F.matmul_psum(x, w, axis_name=MODEL_AXIS,
                                   chunks=4, fuse=True),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
    g0 = F._M_GROUPS.value
    l0 = F._M_LAUNCHES.value
    prog = F.FusedProgram("test/psum", fn, mesh=mesh, chunks=4)
    a = prog(x, w)
    b = prog(x, w)
    assert F._M_GROUPS.value == g0 + 1  # one compile, two launches
    assert F._M_LAUNCHES.value == l0 + 2
    want = np.asarray(fn(x, w)).tobytes()
    assert np.asarray(a).tobytes() == want
    assert np.asarray(b).tobytes() == want


def test_fused_program_ledger_charge_is_scoped_to_the_launch():
    mesh = _mesh()
    x = jnp.ones((16, 8), jnp.float32)
    w = jnp.ones((8, 8), jnp.float32)
    fn = jax.jit(_compat.shard_map(
        lambda x, w: F.matmul_psum(x, w, axis_name=MODEL_AXIS,
                                   chunks=4, fuse=True),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
    nbytes = planner.fused_group_bytes((16, 8), 4)
    led = ledger_mod.ledger
    led.set("fused.launch", 0)
    prog = F.FusedProgram("test/ledger", fn, mesh=mesh, chunks=4,
                          launch_bytes=nbytes)
    prog(x, w)
    # Charged for the launch window, fully released after.
    assert led.bytes_by_category().get("fused.launch", 0) == 0
    if ledger_mod.enabled():
        assert led.peak_by_category().get("fused.launch", 0) >= nbytes


def test_fused_manifest_entry_round_trip(tmp_path, monkeypatch):
    from horovod_tpu.ops import megakernel as mk

    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    mesh = _mesh()
    entry = F.fused_manifest_entry("fused/test.g1", mesh,
                                   [(16, 8), (8, 8)], jnp.float32, 4)
    assert entry["variant"] == "fused"
    assert entry["chunks"] == 4
    mk.record_manifest_entry(entry)
    mk.record_manifest_entry(entry)  # dedup
    got = F.fused_entries(str(tmp_path))
    assert len(got) == 1
    assert got[0]["op"] == "fused/test.g1"
    assert got[0]["chunks"] == 4


def test_fused_group_bytes_formula():
    # Full output + the largest chunk's partial product, in items of
    # the dtype.
    assert planner.fused_group_bytes((16, 8), 4) == (128 + 32) * 4
    # Remainder: ceil(10/4)=3 rows in the largest chunk.
    assert planner.fused_group_bytes((10, 4), 4) == (40 + 12) * 4
    # One chunk: the whole output doubles (reference program).
    assert planner.fused_group_bytes((16, 8), 1) == (128 + 128) * 4
    assert planner.fused_group_bytes((16, 8), 4, dtype="bfloat16") \
        == (128 + 32) * 2


def test_measure_exposed_comm_nonnegative_and_observed():
    from horovod_tpu import telemetry as _telemetry

    x = jnp.ones((64, 64), jnp.float32)
    f = jax.jit(lambda x: x @ x)
    before = _telemetry.registry().histogram(
        "fused.exposed_comm_seconds").snapshot()["count"]
    exposed = F.measure_exposed_comm(f, f, (x,), cycles=3)
    assert exposed >= 0.0
    if _telemetry.enabled():
        after = _telemetry.registry().histogram(
            "fused.exposed_comm_seconds").snapshot()["count"]
        assert after == before + 1
