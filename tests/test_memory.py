"""hvd-mem tests: the device-memory ledger, the static planner, and
the OOM forensics path (horovod_tpu/memory/, docs/memory.md).

Covers the acceptance contracts directly:

* planner determinism — same config ⇒ byte-identical plan JSON;
* planner accuracy — the dataplane/pipeline predictions land within
  ±15 % of the measured ledger high-watermark on the CPU backend;
* seeded RESOURCE_EXHAUSTED (simulated small capacity) produces a
  flight dump naming the failing executable and the top ledger
  categories;
* the flight-recorder metrics tail carries gauges (memory watermarks,
  queue/occupancy) — every dump is self-contained forensics;
* ``serving.kv_free_pages`` rides the KV cache's page management and
  the engine's ``/healthz`` payload.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu.memory as M
from horovod_tpu import telemetry as _telemetry
from horovod_tpu.memory import ledger as ledger_mod
from horovod_tpu.memory import oom as oom_mod
from horovod_tpu.memory import planner


@pytest.fixture()
def fresh_ledger():
    """Isolated ledger (the process-global one keeps its history)."""
    return ledger_mod.MemoryLedger()


@pytest.fixture(autouse=True)
def _zero_prefetch_category():
    """Order-independence for the prefetch accounting tests: a stager
    from an earlier test (any module — prefetch_to_device charges the
    process-global ledger) can land its final put() after that test's
    drain window, leaving a stale "input.prefetch" residue that skews
    this module's peak/zero assertions.  Pin the category to zero on
    entry so every test starts from its own charges only."""
    ledger_mod.ledger.set("input.prefetch", 0)
    yield


# ---------------------------------------------------------------------------
# Ledger
# ---------------------------------------------------------------------------

def test_ledger_alloc_free_and_peaks(fresh_ledger):
    led = fresh_ledger
    led.alloc("a", 100)
    led.alloc("b", 50)
    assert led.total() == 150
    assert led.watermark() == 150
    led.free("a", 60)
    assert led.bytes_by_category() == {"a": 40, "b": 50}
    led.free("a", 999)  # clamped, never negative
    assert led.bytes_by_category()["a"] == 0
    assert led.peak_by_category() == {"a": 100, "b": 50}
    assert led.watermark() == 150  # all-time, survives the frees


def test_ledger_keyed_entries_are_idempotent(fresh_ledger):
    led = fresh_ledger
    led.alloc("kv", 1000, key="engine1")
    led.alloc("kv", 1000, key="engine1")  # re-alloc REPLACES
    assert led.total() == 1000
    led.alloc("kv", 500, key="engine2")
    assert led.total() == 1500
    led.free("kv", key="engine1")
    assert led.total() == 500
    led.free("kv", key="engine1")  # double free: no-op
    assert led.total() == 500


def test_ledger_set_absolute(fresh_ledger):
    led = fresh_ledger
    led.set("residuals", 400)
    led.set("residuals", 100)
    assert led.bytes_by_category()["residuals"] == 100
    assert led.peak_by_category()["residuals"] == 400


def test_ledger_step_watermark_window(fresh_ledger):
    led = fresh_ledger
    led.alloc("x", 100)
    led.free("x", 100)
    assert led.note_step() == 100   # the window saw the transient
    assert led.step_watermark() == 100
    led.alloc("y", 30)              # long-lived store
    assert led.note_step() == 30
    # next window starts at the carried-over total, not zero
    assert led.note_step() == 30
    assert led.steps() == 3


def test_ledger_top_categories(fresh_ledger):
    led = fresh_ledger
    led.alloc("big", 300)
    led.alloc("mid", 200)
    led.alloc("small", 10)
    led.alloc("zero", 0)
    top = led.top(3)
    assert top == [("big", 300), ("mid", 200), ("small", 10)]


def test_ledger_snapshot_names(fresh_ledger):
    led = fresh_ledger
    led.alloc("serving.kv_pages", 64)
    snap = led.snapshot()
    assert snap["memory.bytes.serving.kv_pages"] == 64
    assert snap["memory.ledger_bytes"] == 64
    assert snap["memory.high_watermark_bytes"] == 64


def test_tree_nbytes_counts_array_leaves():
    tree = {"a": np.zeros((4, 4), np.float32),
            "b": [np.zeros((2,), np.float64), 3, "x"]}
    assert ledger_mod.tree_nbytes(tree) == 4 * 4 * 4 + 2 * 8


# ---------------------------------------------------------------------------
# MemoryWatch
# ---------------------------------------------------------------------------

def test_memory_watch_names_leaking_category(fresh_ledger, capsys):
    w = M.MemoryWatch(patience=3, min_growth=100, ledger_=fresh_ledger)
    fired = None
    for i in range(4):
        fired = w.check({"serving.kv_pages": 1000 + i * 200,
                         "input.prefetch": 500})
    assert fired and fired[0]["category"] == "serving.kv_pages"
    assert fired[0]["growth"] == 600
    err = capsys.readouterr().err
    assert "serving.kv_pages" in err and "MemoryWatch" in err


def test_memory_watch_non_monotonic_resets_streak(fresh_ledger):
    w = M.MemoryWatch(patience=3, min_growth=0, ledger_=fresh_ledger)
    sizes = [100, 200, 150, 250, 300, 350]  # dip at step 3
    fired = [w.check({"c": s}) for s in sizes]
    # streak restarts after the dip: grows at steps 4,5,6 -> fires at
    # the THIRD consecutive growth only
    assert fired[:5] == [None] * 5
    assert fired[5] and fired[5][0]["category"] == "c"


def test_memory_watch_min_growth_filters_noise(fresh_ledger):
    w = M.MemoryWatch(patience=2, min_growth=1 << 30,
                      ledger_=fresh_ledger)
    for i in range(6):
        assert w.check({"c": 100 + i}) is None  # tiny growth: quiet


def test_memory_watch_two_leaks_two_warnings(fresh_ledger):
    w = M.MemoryWatch(patience=2, min_growth=10, ledger_=fresh_ledger)
    fired = None
    for i in range(3):
        fired = w.check({"a": 100 + i * 50, "b": 200 + i * 50})
    assert fired and {f["category"] for f in fired} == {"a", "b"}


def test_memory_watch_validates_args(fresh_ledger):
    with pytest.raises(ValueError, match="patience"):
        M.MemoryWatch(patience=1)


def test_memory_watch_reads_global_ledger_counter():
    before = _telemetry.registry().counter(
        "memory.leak_warnings").value
    w = M.MemoryWatch(patience=2, min_growth=1)
    for i in range(3):
        w.check({"c": 100 + i * 10})
    assert _telemetry.registry().counter(
        "memory.leak_warnings").value > before


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def test_plan_json_is_deterministic():
    a = planner.plan_transformer_lm(batch_size=64, world=4).to_json()
    b = planner.plan_transformer_lm(batch_size=64, world=4).to_json()
    assert a == b  # byte-identical (the CI determinism gate)
    assert json.loads(a)["format"] == planner.PLAN_FORMAT


def test_plan_cli_is_deterministic_and_parseable(capsys):
    from horovod_tpu.memory.__main__ import main

    argv = ["--plan", "--model", "serving", "--kv-slots", "16"]
    assert main(argv) == 0
    out1 = capsys.readouterr().out
    assert main(argv) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    plan = json.loads(out1)
    assert plan["framework"]["serving.kv_pages"] == \
        planner.kv_cache_bytes(2, 8, 16, 16, 8, 16)


def test_plan_cli_fit_verdict_rc(capsys):
    from horovod_tpu.memory.__main__ import main

    rc = main(["--plan", "--model", "transformer_lm",
               "--capacity-bytes", "1"])
    assert rc == 3  # scriptable "does not fit"
    plan = json.loads(capsys.readouterr().out)
    assert plan["fits"] is False and plan["headroom_bytes"] < 0
    rc = main(["--plan", "--model", "transformer_lm",
               "--capacity-bytes", str(64 << 30)])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["fits"] is True


def test_plan_pipeline_what_if_schedule():
    """The what-if the CLI answers: GPipe's activation bound grows with
    the microbatch count, 1F1B's stays at the stage depth."""
    f = planner.plan_pipeline(4, 8, 32, 96, 1, schedule="1f1b")
    g = planner.plan_pipeline(4, 8, 32, 96, 1, schedule="gpipe")
    assert g.framework["pipeline.activations"] > \
        f.framework["pipeline.activations"]
    # the CHANGES-documented figures at S=4/m=8: 9 vs GPipe's 24
    assert f.facts["peak_activation_carries"] == 9
    assert g.facts["peak_activation_carries"] == 24


def test_plan_unknown_model_and_optimizer_name_valid_sets():
    with pytest.raises(ValueError, match="dataplane"):
        planner.build_plan("no_such_model")
    with pytest.raises(ValueError, match="adam"):
        planner.plan_transformer_lm(optimizer="adamax")


def test_dtype_bytes_table_and_errors():
    assert planner.dtype_bytes("float32") == 4
    assert planner.dtype_bytes("bfloat16") == 2
    assert planner.dtype_bytes(jnp.dtype("float16")) == 2
    with pytest.raises(ValueError, match="float32"):
        planner.dtype_bytes("floof")


def test_fusion_group_bytes_variants():
    shapes = ((16,), (4, 4))
    # per-replica: world-leading inputs AND outputs
    assert planner.fusion_group_bytes(shapes, "float32", 8, "sp_pr") \
        == 2 * 8 * 32 * 4
    # replicated: single-copy payloads
    assert planner.fusion_group_bytes(shapes, "float32", 8, "sp_rep") \
        == 2 * 32 * 4


def test_record_compiled_harvests_when_backend_supports_it():
    compiled = jax.jit(lambda x: x * 2).lower(
        jnp.zeros((8,), jnp.float32)).compile()
    got = planner.record_compiled("test/exe", compiled)
    table = planner.harvested()
    if got is None:
        # XLA:CPU without memory_analysis: honest absence, no zeros
        assert "test/exe" not in table
    else:
        assert table["test/exe"] == got
        assert all(isinstance(v, int) for v in got.values())
        sect = planner.harvest_section()
        assert sect["coverage"] >= 1
    planner.clear_harvest()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_is_resource_exhausted_detection():
    assert oom_mod.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating"))
    assert oom_mod.is_resource_exhausted(
        oom_mod.ResourceExhaustedError("RESOURCE_EXHAUSTED: sim"))
    assert not oom_mod.is_resource_exhausted(ValueError("shape"))


def _reset_dump_rate_limit():
    """Dumps are rate-limited per reason on the process-global
    recorder; tests that each need their own dump clear the limiter."""
    from horovod_tpu.telemetry import flight as _flight

    with _flight.recorder._dump_lock:
        _flight.recorder._last_dump.clear()


def test_guard_simulated_capacity_dumps_and_raises(tmp_path,
                                                   monkeypatch):
    """The acceptance scenario: a seeded RESOURCE_EXHAUSTED (simulated
    small capacity) produces a flight dump naming the failing
    executable and the top-3 ledger categories."""
    _reset_dump_rate_limit()
    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv(oom_mod.CAPACITY_ENV, str(1 << 20))
    led = ledger_mod.ledger
    led.reset()
    led.alloc("serving.kv_pages", 600_000)
    led.alloc("megakernel.residuals", 300_000)
    led.alloc("input.prefetch", 200_000)
    led.alloc("checkpoint.snapshots", 1)
    try:
        with pytest.raises(oom_mod.ResourceExhaustedError,
                           match="RESOURCE_EXHAUSTED"):
            with oom_mod.guard("megakernel/psum/test",
                               predicted_bytes=500_000):
                raise AssertionError("guard must raise pre-dispatch")
        dumps = glob.glob(str(tmp_path / "*oom*"))
        assert dumps, "no flight dump written"
        payload = json.load(open(dumps[0]))
        extra = payload["extra"]
        assert extra["executable"] == "megakernel/psum/test"
        top = [t["category"] for t in extra["top_categories"]]
        assert top == ["serving.kv_pages", "megakernel.residuals",
                       "input.prefetch"]  # top-3, largest first
        assert extra["predicted_bytes"] == 500_000
        assert extra["advertised_capacity_bytes"] == 1 << 20
        # the metrics tail rides the dump: gauges included (satellite)
        assert payload["metrics"]["memory.ledger_bytes"] \
            == led.total()
    finally:
        led.reset()


def test_guard_captures_real_resource_exhausted(tmp_path, monkeypatch):
    _reset_dump_rate_limit()
    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.delenv(oom_mod.CAPACITY_ENV, raising=False)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        with oom_mod.guard("serving/decode"):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 123 bytes")
    dumps = glob.glob(str(tmp_path / "*oom*"))
    assert dumps
    assert json.load(open(dumps[0]))["extra"]["executable"] \
        == "serving/decode"


def test_guard_passes_other_errors_through_undumped(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    with pytest.raises(ValueError):
        with oom_mod.guard("pipeline/F0"):
            raise ValueError("shape mismatch")
    assert not glob.glob(str(tmp_path / "*oom*"))


def test_capacity_env_validation(monkeypatch):
    monkeypatch.setenv(oom_mod.CAPACITY_ENV, "lots")
    with pytest.raises(ValueError, match="HVD_TPU_MEM_CAPACITY"):
        oom_mod.validate_env()
    monkeypatch.setenv(oom_mod.CAPACITY_ENV, str(1 << 30))
    oom_mod.validate_env()
    assert oom_mod.advertised_capacity() == 1 << 30


def test_preflight_warn_fires_only_over_capacity(monkeypatch, capsys):
    monkeypatch.setenv(oom_mod.CAPACITY_ENV, "1000")
    assert oom_mod.preflight_warn(500, "test") is False
    assert oom_mod.preflight_warn(2000, "test", "params + grads")
    err = capsys.readouterr().err
    assert "pre-flight" in err and "horovod_tpu.memory --plan" in err


def test_live_array_report_shape():
    x = jnp.zeros((16, 16), jnp.float32)
    rep = ledger_mod.live_array_report(top_n=3)
    assert rep["live_bytes"] is None or rep["live_bytes"] >= x.nbytes
    assert isinstance(rep["top"], list)


# ---------------------------------------------------------------------------
# Flight tail + gauge aggregation (satellites)
# ---------------------------------------------------------------------------

def test_flight_tail_carries_memory_and_gauges():
    led = ledger_mod.ledger
    led.reset()
    led.alloc("serving.kv_pages", 12345)
    try:
        tail = _telemetry._flight_metrics_tail()
        assert tail["memory.bytes.serving.kv_pages"] == 12345
        assert tail["memory.ledger_bytes"] == 12345
        # gauge families ride the tail now (not only counters)
        gauge = _telemetry.gauge("serving.kv_free_pages")
        gauge.set(7)
        tail = _telemetry._flight_metrics_tail()
        assert tail["serving.kv_free_pages"] == 7
    finally:
        led.reset()


def test_cluster_aggregation_exact_over_memory_gauges():
    """min/max/mean of the per-rank memory gauges are exact through
    telemetry.aggregate — the arithmetic the np=3 tree leg
    (tests/test_tree.py) asserts over the real wire."""
    snaps = {r: {"memory.ledger_bytes":
                 {"type": "gauge", "value": (r + 1) * 1000}}
             for r in range(3)}
    agg = _telemetry.aggregate(snaps)["memory.ledger_bytes"]
    assert agg["min"] == 1000 and agg["max"] == 3000
    assert agg["mean"] == 2000 and agg["ranks"] == 3
    assert agg["per_rank"] == {0: 1000, 1: 2000, 2: 3000}


# ---------------------------------------------------------------------------
# Allocation sites (KV cache / prefetch / checkpoint / residuals)
# ---------------------------------------------------------------------------

def test_kv_cache_feeds_ledger_and_free_pages_gauge():
    from horovod_tpu.serving.kv_cache import PagedKVCache

    led = ledger_mod.ledger
    led.reset()
    cache = PagedKVCache(n_layers=2, n_heads=4, head_dim=8,
                         max_slots=2, pages_per_slot=4, page_size=8)
    expected = planner.kv_cache_bytes(2, 4, 8, 2, 4, 8)
    assert led.bytes_by_category()["serving.kv_pages"] == expected
    gauge = _telemetry.registry().gauge("serving.kv_free_pages")
    total = _telemetry.registry().gauge("serving.kv_total_pages")
    assert gauge.value == 8 and total.value == 8
    cache.begin_slot(0, 10)  # 2 pages
    assert gauge.value == 6
    cache.free_slot(0)
    assert gauge.value == 8
    del cache
    import gc

    gc.collect()
    assert led.bytes_by_category().get("serving.kv_pages", 0) == 0
    led.reset()


def test_engine_health_includes_kv_free_pages():
    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_transformer)
    from horovod_tpu.serving import InferenceEngine

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=1, d_ff=64, max_seq_len=32)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                          capacity=32)
    ready, payload = eng.health()
    assert payload["kv_free_pages"] == eng.cache.free_pages()
    assert payload["kv_total_pages"] == eng.cache.total_pages
    # page consumption shows up as reduced headroom
    eng.cache.begin_slot(0, 9)
    _, payload2 = eng.health()
    assert payload2["kv_free_pages"] < payload["kv_free_pages"]


def test_prefetch_accounts_staged_batches(hvd):
    from horovod_tpu.parallel.input import prefetch_to_device

    led = ledger_mod.ledger
    led.reset()
    batches = [np.ones((8, 4), np.float32) * i for i in range(4)]
    with prefetch_to_device(iter(batches), depth=2) as it:
        got = next(it)
        assert np.asarray(got)[0, 0] == 0.0
        # whatever is still staged is charged; the consumed one is not
        assert led.peak_by_category().get("input.prefetch", 0) >= \
            batches[0].nbytes
    # close() released everything still queued — including the final
    # batch a stager parked in put() can land AFTER the first drain
    # (the post-join drain in PrefetchIterator.close owns that window).
    assert led.bytes_by_category().get("input.prefetch", 0) == 0
    led.reset()


def test_prefetch_mid_epoch_close_never_leaks_charges(hvd):
    """Regression for the close()-vs-stager race: shutting down with
    the stager mid-stream must drain every charged batch, repeatedly —
    the leaked "input.prefetch" charge was a once-per-hundreds flake,
    so hammer the window."""
    from horovod_tpu.parallel.input import prefetch_to_device

    led = ledger_mod.ledger
    led.reset()
    for trial in range(20):
        batches = (np.full((16, 16), i, np.float32) for i in range(64))
        with prefetch_to_device(batches, depth=2) as it:
            next(it)  # stager now racing to refill the bounded queue
        leaked = led.bytes_by_category().get("input.prefetch", 0)
        assert leaked == 0, (
            f"trial {trial}: {leaked} bytes still charged after close()")
    led.reset()


def test_checkpoint_snapshot_accounting(hvd, tmp_path):
    from horovod_tpu.utils.checkpoint import save_checkpoint

    led = ledger_mod.ledger
    led.reset()
    tree = {"w": np.ones((64, 64), np.float32)}
    h = save_checkpoint(str(tmp_path / "ck.msgpack"), tree)
    assert h.wait(10.0)
    assert led.peak_by_category().get("checkpoint.snapshots", 0) \
        >= tree["w"].nbytes
    assert led.bytes_by_category().get("checkpoint.snapshots", 0) == 0
    led.reset()


# ---------------------------------------------------------------------------
# Accuracy: plan vs measured ledger (the ±15 % contract, CPU backend)
# ---------------------------------------------------------------------------

def _within(pred: int, measured: int, pct: float = 15.0) -> bool:
    return measured > 0 and abs(pred - measured) / measured * 100 <= pct


def test_dataplane_plan_matches_ledger_watermark(hvd):
    """Framework-owned prediction within ±15 % of the measured ledger
    high-watermark for the dataplane workload (the acceptance gate;
    bench.py --mode memory runs the same comparison)."""
    tensors, elems = 8, 128
    n = hvd.size()
    rng = np.random.default_rng(3)
    base = [rng.standard_normal((n, elems)).astype(np.float32)
            for _ in range(tensors)]
    inputs = [hvd.shard(t) for t in base]
    led = ledger_mod.ledger
    led.reset()
    # quiesce: one fused launch deterministically (the planner's model)
    # — the drain tick can no longer split the submissions.
    with hvd.quiesce():
        hs = [hvd.allreduce_async(x, average=True, name=f"mem.{j}")
              for j, x in enumerate(inputs)]
    _ = [hvd.synchronize(h) for h in hs]
    plan = planner.plan_dataplane(tensors, elems, n)
    measured = led.watermark()
    assert _within(plan.framework_bytes, measured), \
        (plan.framework_bytes, measured)
    led.reset()


def test_pipeline_plan_matches_ledger_activations(hvd):
    """Pipeline activation prediction (schedule_plan peak × carry
    bytes) within ±15 % of the measured pipeline.activations peak."""
    S, m, d = 3, 4, 16
    n = hvd.size()

    def stage_first(p, carry, b):
        x, _y = b
        return jnp.tanh(x @ p["w"])

    def stage_mid(p, carry, b):
        return jnp.tanh(carry @ p["w"])

    def stage_last(p, carry, b):
        _x, y = b
        return jnp.mean((carry @ p["w"] - y) ** 2)

    from horovod_tpu.parallel.training import shard_batch

    chain = [stage_first] + [stage_mid] * (S - 2) + [stage_last]
    ks = jax.random.split(jax.random.PRNGKey(0), S)
    params = [{"w": jax.random.normal(k, (d, d)) * d ** -0.5}
              for k in ks]
    B = n * m
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))
    y = jax.random.normal(jax.random.PRNGKey(2), (B, d))
    batch = shard_batch((x, y))
    opt = optax.sgd(0.1)
    step = hvd.make_pipeline_train_step(chain, opt,
                                        num_microbatches=m,
                                        fusion_threshold=d * d * 4)
    led = ledger_mod.ledger
    led.reset()
    p, s, loss = step(params, opt.init(params), batch)
    measured = led.peak_by_category().get("pipeline.activations", 0)
    predicted = planner.pipeline_activation_bytes(
        S, m, microbatch_rows=B // m, width=d)
    assert _within(predicted, measured), (predicted, measured)
    # drained after the step: carries are transient
    assert led.bytes_by_category().get("pipeline.activations", 0) == 0
    # bytes gauge mirrors the peak (the tensors-not-bytes fix)
    snap = hvd.metrics()
    assert snap["pipeline.inflight_activation_bytes"]["value"] \
        == measured
    led.reset()


def test_residual_store_rides_ledger(hvd):
    """Quantized EF residuals appear under megakernel.residuals and
    drain on flush."""
    import horovod_tpu as hv

    from horovod_tpu.ops import megakernel as mk

    led = ledger_mod.ledger
    led.reset()
    hv.set_compression(default="int8")
    try:
        n = hvd.size()
        x = hvd.shard(np.ones((n, 256), np.float32))
        for step_i in range(2):
            h = hvd.allreduce_async(x, average=True, name="resid.t")
            hvd.synchronize(h)
        if mk.residual_count():
            assert led.bytes_by_category().get(
                "megakernel.residuals", 0) > 0
        mk.flush("test")
        assert led.bytes_by_category().get(
            "megakernel.residuals", 0) == 0
    finally:
        hv.set_compression()
    led.reset()


def test_step_watermark_gauge_advances(hvd):
    """make_train_step closes a ledger step window per call (the
    per-step high-watermark surface)."""
    led = ledger_mod.ledger
    led.reset()
    steps0 = led.steps()

    def loss_fn(params, batch):
        return jnp.mean((batch @ params) ** 2)

    from horovod_tpu.parallel.training import (make_train_step,
                                               shard_batch)

    opt = optax.sgd(0.1)
    step = make_train_step(loss_fn, opt, donate=False)
    params = jnp.ones((4, 4), jnp.float32)
    batch = shard_batch(np.ones((hvd.size() * 2, 4), np.float32))
    state = opt.init(params)
    params, state, _loss = step(params, state, batch)
    assert led.steps() == steps0 + 1
    led.reset()
