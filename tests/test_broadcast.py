"""Broadcast tests: root semantics, every root rank, mismatch errors
(≙ reference test_tensorflow.py:429-509, test_torch.py:409-533)."""

import jax.numpy as jnp
import numpy as np
import pytest


def test_broadcast_all_roots(hvd):
    """For every possible root, all replicas receive the root's tensor
    (≙ test_horovod_broadcast, test_tensorflow.py:429-457)."""
    size = hvd.size()
    stack = jnp.stack([jnp.full((3, 3), float(r), jnp.float32)
                       for r in range(size)])
    for root in range(size):
        out = hvd.broadcast(hvd.shard(stack), root_rank=root,
                            name=f"bcast.{root}")
        np.testing.assert_allclose(np.asarray(out),
                                   np.full((3, 3), float(root)))


def test_broadcast_replicated_identity(hvd):
    x = jnp.arange(4.0, dtype=jnp.float32)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def test_broadcast_invalid_root(hvd):
    with pytest.raises(ValueError):
        hvd.broadcast(jnp.ones(2), root_rank=hvd.size())


def test_broadcast_root_rank_mismatch_raises(hvd):
    """Replicas disagreeing on the root is a negotiation error
    (≙ test_horovod_broadcast_rank_error, test_tensorflow.py:459-509)."""
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    # Private coordinator: the shared one is drained by the background
    # tick thread, which would race these direct injections.
    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "bcast.mismatch.root"
    for r in range(hvd.size()):
        coord.submit(Request(r, RequestType.BROADCAST,
                             DataType.FLOAT32, name,
                             root_rank=r % 2, device=-1,
                             tensor_shape=(3,)))
    resps = coord.poll_responses({name: 12})
    assert resps[0].response_type.name == "ERROR"
    assert "Mismatched broadcast root ranks" in resps[0].error_message


def test_broadcast_shape_mismatch_raises(hvd):
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "bcast.mismatch.shape"
    for r in range(hvd.size()):
        shape = (3,) if r % 2 == 0 else (4,)
        coord.submit(Request(r, RequestType.BROADCAST,
                             DataType.FLOAT32, name,
                             root_rank=0, device=-1,
                             tensor_shape=shape))
    resps = coord.poll_responses({name: 12})
    assert resps[0].response_type.name == "ERROR"
    assert "Mismatched broadcast tensor shapes" in resps[0].error_message


def test_broadcast_parameters_pytree(hvd):
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4),
              "nested": {"x": jnp.full((2,), 7.0)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert set(out.keys()) == {"w", "b", "nested"}
    np.testing.assert_allclose(np.asarray(out["nested"]["x"]),
                               np.full((2,), 7.0), rtol=1e-6)
