"""Alltoall + barrier (post-v0.13 ``hvd.alltoall`` / ``hvd.barrier``;
the v0.13 reference has neither).  Self-verifying against hand-built
send matrices; the cross-process legs ride the mp ``basic`` scenario.
"""

import jax.numpy as jnp
import numpy as np
import pytest


def test_alltoall_even(hvd):
    """Each replica sends one row to every destination; receiver r sees
    senders' rows in rank order."""
    n = hvd.size()
    rows = np.zeros((n, n, 2), np.float32)
    for s in range(n):
        for d in range(n):
            rows[s, d] = s * 10 + d
    outs = hvd.alltoall(hvd.shard(jnp.asarray(rows)))
    assert len(outs) == n
    for r in range(n):
        np.testing.assert_allclose(
            np.asarray(outs[r])[:, 0], [s * 10 + r for s in range(n)])


def test_alltoall_ragged_splits(hvd):
    """Uneven splits: receivers get differing row counts, zero included."""
    n = hvd.size()
    splits = [0] * n
    splits[1] = n  # every sender directs ALL rows to receiver 1
    rows = np.stack([np.arange(float(n)) + 100 * s
                     for s in range(n)])[..., None]
    outs = hvd.alltoall(hvd.shard(jnp.asarray(rows)), splits=splits)
    assert np.asarray(outs[0]).shape == (0, 1)
    got = np.asarray(outs[1])[:, 0]
    want = np.concatenate([np.arange(float(n)) + 100 * s
                           for s in range(n)])
    np.testing.assert_allclose(got, want)


def test_alltoall_replicated_and_process_set(hvd):
    n = hvd.size()
    # Replicated input: every replica sends the same [n] rows evenly.
    outs = hvd.alltoall(jnp.arange(float(n)))
    np.testing.assert_allclose(np.asarray(outs[2]), [2.0] * n)
    ps = hvd.add_process_set([0, 1])
    outs = hvd.alltoall(jnp.arange(2.0), process_set=ps)
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[1]), [1.0, 1.0])


def test_alltoall_validation(hvd):
    n = hvd.size()
    with pytest.raises(ValueError, match="divisible"):
        hvd.alltoall(jnp.ones((n + 1,)))
    with pytest.raises(ValueError, match="entry per rank"):
        hvd.alltoall(jnp.ones((n,)), splits=[n])
    with pytest.raises(ValueError, match="not a list"):
        hvd.alltoall([jnp.ones((n,))] * n)


def test_barrier_is_a_real_collective(hvd):
    hvd.barrier()  # completes on the full negotiation path
    ps = hvd.add_process_set([0, 1, 2])
    hvd.barrier(process_set=ps)


def test_alltoall_scalar_raises_cleanly(hvd):
    with pytest.raises(ValueError, match="at least one dimension"):
        hvd.alltoall(jnp.asarray(1.0))
