"""hvd-pipeline input half: double-buffered device prefetch
(parallel/input.py), the batched device_put satellites, the async
train-loop plumbing (barrier_fence, the in-flight window) and the
host-stall telemetry."""

import threading
import time
import traceback

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu
from horovod_tpu.parallel.input import (PrefetchIterator, device_put_batch,
                                        prefetch_to_device)
from horovod_tpu.parallel.training import (barrier_fence, batch_sharding,
                                           make_train_step, shard_batch,
                                           shard_parallel_batch)


def _batches(n, rows=16, cols=4, tag=0):
    for i in range(n):
        rng = np.random.RandomState(100 * tag + i)
        yield {"x": rng.normal(size=(rows, cols)).astype("float32"),
               "i": np.full((rows,), i, dtype="int32")}


# ---------------------------------------------------------------------------
# Prefetch contract
# ---------------------------------------------------------------------------

def test_prefetch_preserves_order_and_values(hvd):
    got = list(prefetch_to_device(_batches(6)))
    assert len(got) == 6
    for i, (ref, dev) in enumerate(zip(_batches(6), got)):
        assert int(dev["i"][0]) == i
        np.testing.assert_array_equal(np.asarray(dev["x"]), ref["x"])
        # Correct per-leaf placement: the data-parallel default sharding.
        assert dev["x"].sharding == batch_sharding()


def test_prefetch_bounded_depth(hvd):
    """The stager never runs more than ``depth`` batches ahead of the
    consumer (plus the one it is currently staging)."""
    produced = []

    def loader():
        for i in range(20):
            produced.append(i)
            yield np.full((8,), i, dtype="float32")

    it = prefetch_to_device(loader(), depth=2)
    time.sleep(0.5)  # let the stager run as far ahead as it ever will
    # depth staged + at most one in the stager's hands.
    assert len(produced) <= 2 + 1, produced
    consumed = 0
    for _ in it:
        consumed += 1
        if consumed == 10:
            time.sleep(0.2)
            assert len(produced) <= consumed + 2 + 1, (len(produced),
                                                       consumed)
    assert consumed == 20
    it.close()


def test_prefetch_depth_validation(hvd):
    with pytest.raises(ValueError, match="depth"):
        prefetch_to_device(_batches(1), depth=0)


def test_prefetch_loader_exception_propagates_with_traceback(hvd):
    """A loader crash re-raises at the consuming step — the ORIGINAL
    exception object, stager-side frames intact — and is flight-recorded."""
    def exploding():
        yield np.zeros((8,), "float32")
        raise ValueError("corrupt shard 7")

    errors_before = horovod_tpu.metrics().get(
        "input.prefetch_errors", {}).get("value", 0)
    it = prefetch_to_device(exploding(), depth=2)
    next(it)
    with pytest.raises(ValueError, match="corrupt shard 7") as exc_info:
        next(it)
    tb = "".join(traceback.format_exception(
        exc_info.type, exc_info.value, exc_info.tb))
    assert "exploding" in tb  # the loader frame survived the thread hop
    # Exhausted after the error: the iterator is dead, not wedged.
    with pytest.raises(StopIteration):
        next(it)
    errors_after = horovod_tpu.metrics()[
        "input.prefetch_errors"]["value"]
    assert errors_after == errors_before + 1


def test_prefetch_clean_shutdown_mid_epoch(hvd):
    """close() with a full queue and an unfinished loader: the stager
    thread exits, the generator is closed, nothing deadlocks."""
    closed = threading.Event()

    def loader():
        try:
            for i in range(1000):
                yield np.full((8,), i, dtype="float32")
        finally:
            closed.set()

    it = prefetch_to_device(loader(), depth=2)
    assert int(np.asarray(next(it))[0]) == 0
    it.close()
    assert closed.wait(5.0), "generator close() never ran"
    assert not it._thread.is_alive()
    with pytest.raises(StopIteration):
        next(it)
    it.close()  # idempotent


def test_prefetch_close_wakes_blocked_consumer(hvd):
    """close() from ANOTHER thread while the consumer is parked waiting
    on an empty queue must wake the consumer (StopIteration), not leave
    it blocked forever (review finding: the stager exits via the stop
    flag without enqueuing an end marker)."""
    def never_yields():
        time.sleep(30.0)
        yield np.zeros((8,), "float32")

    it = prefetch_to_device(never_yields(), depth=1)
    threading.Timer(0.2, it.close).start()
    t0 = time.time()
    with pytest.raises(StopIteration):
        next(it)
    assert time.time() - t0 < 5.0, "consumer stayed blocked after close()"


def test_prefetch_context_manager_and_break(hvd):
    with prefetch_to_device(_batches(100), depth=2) as it:
        for k, _ in enumerate(it):
            if k == 3:
                break
    assert not it._thread.is_alive()


def test_prefetch_custom_sharding_tree(hvd):
    """Per-leaf PartitionSpec pytrees place each leaf independently
    (the multi-axis shard_parallel_batch layouts)."""
    mesh = horovod_tpu.mesh()
    spec = {"x": P("hvd"), "w": P()}
    def loader():
        yield {"x": np.zeros((8, 2), "float32"),
               "w": np.ones((3,), "float32")}
    got = next(prefetch_to_device(loader(), sharding=spec))
    assert got["x"].sharding == NamedSharding(mesh, P("hvd"))
    assert got["w"].sharding == NamedSharding(mesh, P())


def test_prefetch_host_stall_metric(hvd):
    """A loader slower than the consumer shows up in host.stall_seconds."""
    before = horovod_tpu.metrics().get(
        "host.stall_seconds", {}).get("count", 0)

    def slow():
        for i in range(3):
            time.sleep(0.05)
            yield np.zeros((8,), "float32")

    list(prefetch_to_device(slow(), depth=1))
    snap = horovod_tpu.metrics()["host.stall_seconds"]
    assert snap["count"] > before
    assert snap["sum"] > 0.0


# ---------------------------------------------------------------------------
# Batched device_put satellites
# ---------------------------------------------------------------------------

def test_shard_batch_single_call_tree(hvd):
    """shard_batch is now ONE device_put over the whole tree and must
    preserve the per-leaf values + sharding of the old per-leaf loop."""
    tree = {"a": np.arange(32, dtype="float32").reshape(8, 4),
            "b": (np.ones((8, 2), "int32"), np.zeros((8,), "float32"))}
    out = shard_batch(tree)
    sh = batch_sharding()
    for ref, dev in zip(jax.tree_util.tree_leaves(tree),
                        jax.tree_util.tree_leaves(out)):
        np.testing.assert_array_equal(np.asarray(dev), ref)
        assert dev.sharding == sh


def test_shard_parallel_batch_single_call_specs(hvd):
    mesh = horovod_tpu.mesh()
    batch = (np.zeros((8, 4), "float32"), np.ones((2, 2), "float32"))
    out = shard_parallel_batch(batch, mesh, (P("hvd", None), P()))
    assert out[0].sharding == NamedSharding(mesh, P("hvd", None))
    assert out[1].sharding == NamedSharding(mesh, P())
    # Single-spec broadcast form.
    out2 = shard_parallel_batch(batch[0], mesh, P("hvd"))
    assert out2.sharding == NamedSharding(mesh, P("hvd"))


def test_device_put_batch_defaults(hvd):
    out = device_put_batch({"x": np.zeros((8, 3), "float32")})
    assert out["x"].sharding == batch_sharding()


# ---------------------------------------------------------------------------
# barrier_fence + the async-dispatch step window
# ---------------------------------------------------------------------------

def test_barrier_fence_blocks_on_trees_and_devices(hvd):
    x = jnp.arange(8.0)
    y = jax.jit(lambda a: a * 2)(x)
    barrier_fence(y)          # explicit-tree form
    barrier_fence()           # whole-mesh drain form
    np.testing.assert_array_equal(np.asarray(y), np.arange(8.0) * 2)


def test_train_loop_prefetched_matches_synchronous(hvd):
    """The full overlapped loop (prefetch + deferred fetch + fence) is
    bitwise-identical to the synchronous shard_batch/float(loss) loop."""
    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    opt = optax.sgd(0.05)
    step = make_train_step(loss_fn, opt, donate=False)
    params0 = {"w": jnp.zeros((4, 1))}

    def data(n=8):
        for i in range(n):
            rng = np.random.RandomState(i)
            yield {"x": rng.normal(size=(16, 4)).astype("float32"),
                   "y": rng.normal(size=(16, 1)).astype("float32")}

    # Synchronous leg.
    p_sync, s_sync = params0, opt.init(params0)
    for b in data():
        p_sync, s_sync, loss = step(p_sync, s_sync, shard_batch(b))
        float(loss)

    # Overlapped leg.
    p_async, s_async = params0, opt.init(params0)
    with prefetch_to_device(data(), depth=2) as staged:
        for b in staged:
            p_async, s_async, loss = step(p_async, s_async, b)
    barrier_fence(p_async)
    assert (np.asarray(p_sync["w"]).tobytes()
            == np.asarray(p_async["w"]).tobytes())


def test_trainer_prefetch_and_log_every(hvd):
    """Trainer.fit's built-in prefetch produces the same history as the
    synchronous path, and log_every hands a fetched loss to the
    callbacks at the chosen cadence only."""
    from horovod_tpu.frontends.loop import Trainer

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    def batches(epoch, step):
        rng = np.random.RandomState(epoch * 100 + step)
        return (rng.normal(size=(16, 4)).astype("float32"),
                rng.normal(size=(16, 1)).astype("float32"))

    fetched = []

    class Spy:
        def on_batch_end(self, step, logs=None):
            if logs is not None:
                fetched.append((step, logs["loss"]))

    params0 = {"w": jnp.zeros((4, 1))}
    t1 = Trainer(loss_fn, params0, lr=0.05, callbacks=[Spy()])
    h1 = t1.fit(batches, epochs=2, steps_per_epoch=6, log_every=3)
    assert [s for s, _ in fetched] == [2, 5, 2, 5]
    assert all(np.isfinite(v) for _, v in fetched)

    t2 = Trainer(loss_fn, params0, lr=0.05)
    h2 = t2.fit(batches, epochs=2, steps_per_epoch=6, prefetch=0)
    assert h1 == h2  # overlap reorders host work, never arithmetic
    assert (np.asarray(t1.params["w"]).tobytes()
            == np.asarray(t2.params["w"]).tobytes())


def test_throttled_step_survives_donation(hvd):
    """The in-flight window blocks on PAST outputs whose buffers may
    have been donated into the next dispatch — it must skip the deleted
    leaves instead of raising (the depth>=2 regression)."""
    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"]) ** 2)

    opt = optax.sgd(0.01)
    step = make_train_step(loss_fn, opt, donate=True)
    params = {"w": jnp.ones((4, 1))}
    opt_state = opt.init(params)
    batch = shard_batch(np.ones((8, 4), "float32"))
    for _ in range(6):  # > window depth: exercises the popleft path
        params, opt_state, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))
