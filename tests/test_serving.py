"""hvd-serve: scheduler unit tests (no XLA), paged KV cache, the
incremental-decode bitwise contract, engine/executable behavior, the
HTTP front door on the shared exporter, and elastic drain/resume.

The load-bearing assertion (ISSUE 7 acceptance): prefill + N decode
steps through the cached donated executables reproduce the jitted
non-incremental ``serving_forward`` BITWISE — greedy completions are
therefore invariant to batch composition, slot assignment, scheduler
policy, and engine relaunches, which is what makes continuous batching
and elastic resize observably side-effect-free.
"""

import json
import os
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (TransformerConfig,
                                            forward_step,
                                            init_transformer,
                                            serving_forward)
from horovod_tpu.serving import (ContinuousBatchingScheduler,
                                 FinishReason, InferenceEngine, LMServer,
                                 PagedKVCache, Request)

CFG = TransformerConfig(vocab_size=97, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq_len=64)
PARAMS = init_transformer(jax.random.PRNGKey(0), CFG)


def make_engine(**kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("page_size", 8)
    kw.setdefault("capacity", 32)
    return InferenceEngine(PARAMS, CFG, **kw)


def reference_rollout(prompt, n, capacity, params=PARAMS, cfg=CFG):
    """Greedy rollout through the jitted NON-incremental forward."""
    sf = jax.jit(serving_forward, static_argnums=(2, 3))
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = np.asarray(sf(params, jnp.asarray([seq], jnp.int32),
                               cfg, capacity))
        tok = int(np.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


# ---------------------------------------------------------------------------
# Scheduler (pure unit — no XLA)
# ---------------------------------------------------------------------------

def _req(prompt=(1, 2, 3), **kw):
    kw.setdefault("max_new_tokens", 4)
    return Request(prompt=list(prompt), **kw)


def test_scheduler_admission_is_fifo_lowest_slot_first():
    s = ContinuousBatchingScheduler(max_slots=2, capacity=32)
    r1, r2, r3 = (s.submit(_req()) for _ in range(3))
    admitted = s.admit()
    assert [(slot, r.rid) for slot, r in admitted] == [(0, r1.rid),
                                                      (1, r2.rid)]
    assert s.queue_depth() == 1 and s.occupancy() == 2
    # r3 must wait; no later arrival can jump it.
    r4 = s.submit(_req())
    assert s.admit() == []
    # Evict slot 1 -> next admit takes THE HEAD (r3) into slot 1.
    for _ in range(4):
        s.feed(1, 9)
    assert r2.finish_reason == FinishReason.MAX_NEW_TOKENS
    admitted = s.admit()
    assert [(slot, r.rid) for slot, r in admitted] == [(1, r3.rid)]
    assert s.queue_depth() == 1 and r4.done.is_set() is False


def test_scheduler_eviction_reasons_and_slot_reuse():
    s = ContinuousBatchingScheduler(max_slots=1, capacity=8)
    r_eos = s.submit(_req(max_new_tokens=10, eos_id=42))
    s.admit()
    assert s.feed(0, 7) is None
    assert s.feed(0, 42) == FinishReason.EOS
    assert r_eos.result(0) == [7, 42]
    # Slot 0 reusable immediately (iteration-level eviction).
    r_cap = s.submit(_req(prompt=[1, 2, 3, 4, 5], max_new_tokens=10))
    assert s.admit()[0][0] == 0
    assert s.feed(0, 1) is None  # 5 + 2 < 8
    assert s.feed(0, 1) is None
    assert s.feed(0, 1) == FinishReason.CAPACITY
    r_max = s.submit(_req(max_new_tokens=1))
    s.admit()
    assert s.feed(0, 3) == FinishReason.MAX_NEW_TOKENS
    assert r_max.result(0) == [3]
    assert r_cap.finish_reason == FinishReason.CAPACITY


def test_scheduler_starvation_freedom_under_full_batch():
    """Adversarial: a stream of long jobs keeps the batch full; the
    head-of-queue short job is still admitted within a bounded number
    of iterations (FIFO — nothing can overtake it)."""
    s = ContinuousBatchingScheduler(max_slots=2, capacity=1000)
    long_reqs = [s.submit(_req(max_new_tokens=100)) for _ in range(2)]
    s.admit()
    victim = s.submit(_req(max_new_tokens=1))
    # Keep submitting fresh long jobs behind the victim every iteration.
    for it in range(200):
        s.submit(_req(max_new_tokens=100))
        for slot, r in s.active():
            s.feed(slot, 5)
        admitted = s.admit()
        if any(r is victim for _, r in admitted):
            break
    else:
        pytest.fail("victim request was starved")
    # Admitted as soon as the first long job finished (100 iterations).
    assert it <= 100


def test_scheduler_deterministic_composition_from_seeded_trace():
    def run():
        rng = np.random.default_rng(3)
        s = ContinuousBatchingScheduler(max_slots=3, capacity=64)
        log = []
        reqs = []
        for it in range(40):
            if rng.random() < 0.6:
                reqs.append(s.submit(_req(
                    max_new_tokens=int(rng.integers(1, 6)),
                    arrival=it)))
            for slot, r in s.active():
                s.feed(slot, int(rng.integers(0, 9)))
            log.append(tuple((slot, r.rid)
                             for slot, r in s.admit(now=it)))
            log.append(tuple(slot for slot, _ in s.active()))
        return log

    assert run() == run()


def test_scheduler_arrival_gating_and_drain():
    s = ContinuousBatchingScheduler(max_slots=2, capacity=32)
    r = s.submit(_req(arrival=5))
    assert s.admit(now=4) == []
    assert [x[1] for x in s.admit(now=5)] == [r]
    s.feed(0, 1)
    drained, pending = s.drain()
    assert drained == [r] and pending == []
    assert r.finish_reason == FinishReason.DRAINED
    assert r.result(0) == [1]
    with pytest.raises(RuntimeError):
        s.submit(_req())
    s.resume()
    s.submit(_req())
    assert len(s.admit()) == 1


def test_scheduler_snapshot_is_one_lock_hold_and_drain_reason():
    """snapshot() returns (active, pending) atomically — the export
    path's view; drain(reason) finishes in-flight sequences with the
    caller's reason (set BEFORE done, so a blocked handler can never
    read a stale one) and returns raced pending submissions too."""
    s = ContinuousBatchingScheduler(max_slots=1, capacity=32)
    r1 = s.submit(_req())
    s.admit()
    r2 = s.submit(_req())
    active, pending = s.snapshot()
    assert active == [(0, r1)] and pending == [r2]
    drained, pending = s.drain(FinishReason.ERROR)
    assert drained == [r1] and pending == [r2]
    assert r1.finish_reason == FinishReason.ERROR and r1.done.is_set()
    # Pending requests are returned for the CALLER to fail/requeue —
    # drain itself must not touch them (the elastic path resubmits).
    assert r2.finish_reason is None and not r2.done.is_set()


def test_scheduler_feed_expect_tolerates_concurrent_eviction():
    """feed(expect=req): when a concurrent drain evicted the slot (or
    another request now holds it), the token is discarded and the
    evicted request's finish reason is returned instead of raising —
    a drain landing mid-iteration must not poison the step."""
    s = ContinuousBatchingScheduler(max_slots=1, capacity=32)
    r1 = s.submit(_req())
    s.admit()
    s.drain()
    assert s.feed(0, 7, expect=r1) == FinishReason.DRAINED
    assert r1.generated == []  # the token was discarded
    with pytest.raises(ValueError):
        s.feed(0, 7)  # without expect the strict contract remains


def test_scheduler_rejects_bad_prompts():
    s = ContinuousBatchingScheduler(max_slots=1, capacity=8)
    with pytest.raises(ValueError):
        s.submit(_req(prompt=[]))
    with pytest.raises(ValueError):
        s.submit(_req(prompt=list(range(8))))  # no room to generate


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------

def test_kv_cache_page_lifecycle_and_reuse():
    c = PagedKVCache(n_layers=2, n_heads=4, head_dim=16, max_slots=2,
                     pages_per_slot=4, page_size=8)
    assert c.n_pages == 9 and c.free_pages() == 8  # page 0 reserved
    c.begin_slot(0, 10)  # 10 tokens -> 2 pages
    assert c.free_pages() == 6 and c.length(0) == 10
    first_pages = list(c._table[0][:2])
    assert 0 not in first_pages
    c.ensure(0, 16)  # 3rd page
    assert c.free_pages() == 5
    c.free_slot(0)
    assert c.free_pages() == 8 and c.length(0) == -1
    # Recycled pages serve the next sequence.
    c.begin_slot(1, 30)
    assert c.free_pages() == 4
    with pytest.raises(ValueError):
        c.ensure(1, 32)  # beyond per-slot capacity
    with pytest.raises(ValueError):
        c.begin_slot(1, 2)  # already active


def test_kv_cache_ensure_on_freed_slot_is_a_leakfree_noop():
    """Regression (drain-vs-serve-loop page leak): step() reads
    length(slot) and calls ensure(slot, n) as two lock holds, so a
    drain freeing the slot between them must make ensure a no-op —
    pages mapped into a freed slot are unreachable forever (free_slot
    early-returns on length < 0 and begin_slot zeroes the row)."""
    c = PagedKVCache(n_layers=1, n_heads=4, head_dim=8, max_slots=2,
                     pages_per_slot=4, page_size=8)
    c.begin_slot(0, 10)
    n = c.length(0)
    c.free_slot(0)  # the concurrent drain lands here
    free_before = c.free_pages()
    c.ensure(0, n)  # the loop's stale call: must not map pages
    assert c.free_pages() == free_before
    assert list(c._table[0]) == [0] * 4
    c.begin_slot(0, 10)  # slot stays reusable, no pages lost
    c.free_slot(0)
    assert c.free_pages() == c.n_pages - 1


def test_kv_cache_sharding_requires_model_axis():
    c = PagedKVCache(n_layers=1, n_heads=4, head_dim=8, max_slots=1,
                     pages_per_slot=2, page_size=4)
    assert c.page_sharding() is None  # no mesh


# ---------------------------------------------------------------------------
# Shared-prefix page cache (hvd-spec)
# ---------------------------------------------------------------------------

def _prefix_cache(**kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("pages_per_slot", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("fingerprint", "test-model")
    return PagedKVCache(n_layers=1, n_heads=2, head_dim=8, **kw)


def test_prefix_publish_lookup_chain_semantics():
    c = _prefix_cache()
    prompt = list(range(20))  # 2 full pages + 4 tokens
    c.begin_slot(0, len(prompt))
    assert c.publish_prefix(0, prompt) == 2
    # Longest cached page-aligned STRICT prefix: the full 2 pages for
    # an extending prompt, 1 page when only the first page matches,
    # nothing for a diverging first page.
    assert len(c.lookup_prefix(prompt + [50, 51])) == 2
    assert len(c.lookup_prefix(prompt[:8] + [99] * 12)) == 1
    assert c.lookup_prefix([99] + prompt) == []
    # Strictness: a prompt that IS the cached prefix exactly keeps at
    # least one suffix token to prefill.
    assert len(c.lookup_prefix(prompt[:16])) == 1
    # The chain hash commits to every earlier token: same page-2
    # content after a different page 1 must miss.
    assert len(c.lookup_prefix([98] * 8 + prompt[8:16] + [1])) == 0


def test_prefix_refcount_lru_and_reclaim():
    c = _prefix_cache(max_slots=2, pages_per_slot=4)
    prompt = list(range(17))  # 2 full pages
    c.begin_slot(0, len(prompt))
    c.publish_prefix(0, prompt)
    pages = c.lookup_prefix(prompt + [1])
    stats = c.prefix_stats()
    assert stats["cached_pages"] == 2
    assert stats["referenced_pages"] == 2  # slot 0 holds them
    assert stats["reclaimable_pages"] == 0
    # A second slot maps them copy-free; refcounts go to 2.
    c.begin_slot(1, len(prompt) + 3, prefix_pages=pages)
    assert list(c._table[1][:2]) == pages
    assert c.prefix_stats()["referenced_pages"] == 2
    c.free_slot(0)
    assert c.prefix_stats()["referenced_pages"] == 2  # slot 1 remains
    c.free_slot(1)
    stats = c.prefix_stats()
    # Unreferenced but still cached: parked in the reclaimable LRU,
    # counted as free headroom.
    assert stats["referenced_pages"] == 0
    assert stats["reclaimable_pages"] == 2
    assert c.free_pages() == c.total_pages
    # Pressure reclaims LRU pages (and drops their index entries) but
    # NEVER a referenced one.
    c.begin_slot(0, 32)  # all 4 pages of slot 0
    c.begin_slot(1, 32)  # exhausts the free list + both LRU pages
    assert c.prefix_stats()["cached_pages"] == 0
    assert len(c.lookup_prefix(prompt + [1])) == 0


def test_prefix_referenced_pages_never_reclaimed():
    """Pressure reclaims only UNREFERENCED cached pages: with a ghost
    chain parked in the LRU and a referenced shared page live, filling
    the store consumes the LRU and leaves the referenced page (and
    slot 0's mapping of it) untouched."""
    c = _prefix_cache(max_slots=2, pages_per_slot=4)
    prompt = list(range(9))  # 1 full page
    c.begin_slot(0, len(prompt))
    c.publish_prefix(0, prompt)          # page referenced by slot 0
    c.ensure(0, 31)                      # slot 0 holds all 4 pages
    ghost_tokens = list(range(60, 76))
    c.publish_ghost(c.alloc_ghost(2), ghost_tokens)
    assert c.prefix_stats()["reclaimable_pages"] == 2
    assert c.free_pages() == 4           # 2 free-list + 2 reclaimable
    shared_page = int(c._table[0][0])
    c.begin_slot(1, 32)                  # needs 4 -> reclaims the LRU
    stats = c.prefix_stats()
    assert stats["reclaimable_pages"] == 0
    assert stats["cached_pages"] == 1    # the referenced page survives
    assert int(c._table[0][0]) == shared_page
    assert c.lookup_prefix(ghost_tokens + [1]) == []


def test_prefix_ghost_seed_roundtrip():
    c = _prefix_cache()
    tokens = list(range(16))  # exactly 2 pages
    row = c.alloc_ghost(2)
    assert c.publish_ghost(row, tokens) == 2
    stats = c.prefix_stats()
    assert stats["cached_pages"] == 2
    assert stats["reclaimable_pages"] == 2  # refcount zero, hittable
    assert len(c.lookup_prefix(tokens + [7])) == 2
    # Export returns the maximal chain only.
    assert c.export_prefixes() == [tokens]
    # Re-publishing the same chain frees the duplicate pages back.
    free_before = c.free_pages()
    row2 = c.alloc_ghost(2)
    assert c.publish_ghost(row2, tokens) == 0
    assert c.free_pages() == free_before


def test_prefix_disabled_cache_is_inert():
    c = _prefix_cache(prefix_cache=False)
    prompt = list(range(20))
    c.begin_slot(0, len(prompt))
    assert c.publish_prefix(0, prompt) == 0
    assert c.lookup_prefix(prompt + [1]) == []
    assert c.prefix_stats()["cached_pages"] == 0


# ---------------------------------------------------------------------------
# Incremental decode: the bitwise contract (model level)
# ---------------------------------------------------------------------------

def test_prefill_plus_decode_bitwise_equals_noncached_forward():
    """THE satellite contract: prefill + N width-2 decode steps through
    jitted forward_step reproduce the non-incremental forward bitwise
    (same jit, any split point)."""
    b, P, N, cap = 2, 7, 9, 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, P + N), 0,
                                CFG.vocab_size).astype(jnp.int32)
    hd = CFG.d_model // CFG.n_heads
    zeros = jnp.zeros((CFG.n_layers, b, cap, CFG.n_heads, hd), CFG.dtype)
    z = jnp.zeros((b,), jnp.int32)
    step = jax.jit(forward_step, static_argnums=(5,))
    ref, _, _ = step(PARAMS, tokens, z, zeros, zeros, CFG)
    ref = np.asarray(ref)

    def scatter(view, new, start):
        return jax.vmap(
            lambda vb, nb, s: jax.lax.dynamic_update_slice_in_dim(
                vb, nb, s, axis=1),
            in_axes=(1, 1, 0), out_axes=1)(view, new, start)

    k, v = zeros, zeros
    logits, kn, vn = step(PARAMS, tokens[:, :P], z, k, v, CFG)
    assert np.asarray(logits).tobytes() == ref[:, :P].tobytes()
    k, v = scatter(k, kn, z), scatter(v, vn, z)
    for t in range(N):
        pos = jnp.full((b,), P + t, jnp.int32)
        blk = jnp.concatenate(
            [tokens[:, P + t:P + t + 1],
             jnp.zeros((b, 1), jnp.int32)], axis=1)  # width-2 block
        logits, kn, vn = step(PARAMS, blk, pos, k, v, CFG)
        assert (np.asarray(logits)[:, :1].tobytes()
                == ref[:, P + t:P + t + 1].tobytes()), f"step {t}"
        k = scatter(k, kn[:, :, :1], pos)
        v = scatter(v, vn[:, :, :1], pos)


def test_decode_at_final_capacity_position_is_bitwise():
    """Regression (width-2 decode at the capacity boundary): a decode
    block [token, dummy] landing at start == capacity-1 used to go
    through a clamped slice-update that shifted the whole window back
    one position — overwriting the previous token's K/V with the
    current token's and leaving the dummy's K/V unmasked at
    capacity-1.  forward_step must instead keep the real token at its
    true index and drop the dummy column."""
    b, cap = 2, 16
    tokens = jax.random.randint(jax.random.PRNGKey(4), (b, cap), 0,
                                CFG.vocab_size).astype(jnp.int32)
    hd = CFG.d_model // CFG.n_heads
    zeros = jnp.zeros((CFG.n_layers, b, cap, CFG.n_heads, hd), CFG.dtype)
    z = jnp.zeros((b,), jnp.int32)
    step = jax.jit(forward_step, static_argnums=(5,))
    ref, _, _ = step(PARAMS, tokens, z, zeros, zeros, CFG)
    # Prefill the first cap-1 positions, then decode the final one.
    _, kn, vn = step(PARAMS, tokens[:, :cap - 1], z, zeros, zeros, CFG)
    k = zeros.at[:, :, :cap - 1].set(kn)
    v = zeros.at[:, :, :cap - 1].set(vn)
    pos = jnp.full((b,), cap - 1, jnp.int32)
    blk = jnp.concatenate([tokens[:, cap - 1:],
                           jnp.zeros((b, 1), jnp.int32)], axis=1)
    logits, kn2, _ = step(PARAMS, blk, pos, k, v, CFG)
    assert (np.asarray(logits)[:, 0].tobytes()
            == np.asarray(ref)[:, cap - 1].tobytes())
    # The returned new-token K is the real token's (scatter-back input).
    _, k_ref, _ = step(PARAMS, tokens, z, zeros, zeros, CFG)
    assert (np.asarray(kn2[:, :, 0]).tobytes()
            == np.asarray(k_ref[:, :, cap - 1]).tobytes())


def test_ragged_batch_masking_matches_per_sequence_runs():
    """Cache-aware causal masking for ragged batches: each row of a
    mixed-length decode batch is bitwise what it would be alone."""
    cap = 16
    hd = CFG.d_model // CFG.n_heads
    step = jax.jit(forward_step, static_argnums=(5,))

    def kv(b):
        return jnp.zeros((CFG.n_layers, b, cap, CFG.n_heads, hd),
                         CFG.dtype)

    t1 = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0,
                            CFG.vocab_size).astype(jnp.int32)
    t2 = jax.random.randint(jax.random.PRNGKey(3), (1, 9), 0,
                            CFG.vocab_size).astype(jnp.int32)
    z1 = jnp.zeros((1,), jnp.int32)
    _, k1, v1 = step(PARAMS, t1, z1, kv(1), kv(1), CFG)
    _, k2, v2 = step(PARAMS, t2, z1, kv(1), kv(1), CFG)

    def install(view, new, row):
        return view.at[:, row, :new.shape[2]].set(new[:, 0])

    # Batched ragged decode: row 0 at position 5, row 1 at position 9.
    kb = install(install(kv(2), k1, 0), k2, 1)
    vb = install(install(kv(2), v1, 0), v2, 1)
    toks = jnp.asarray([[7, 0], [11, 0]], jnp.int32)
    lengths = jnp.asarray([5, 9], jnp.int32)
    lb, _, _ = step(PARAMS, toks, lengths, kb, vb, CFG)
    # Per-sequence singles (batch independence is part of the contract).
    la, _, _ = step(PARAMS, jnp.asarray([[7, 0]], jnp.int32),
                    jnp.asarray([5], jnp.int32),
                    install(kv(1), k1, 0), install(kv(1), v1, 0), CFG)
    lc, _, _ = step(PARAMS, jnp.asarray([[11, 0]], jnp.int32),
                    jnp.asarray([9], jnp.int32),
                    install(kv(1), k2, 0), install(kv(1), v2, 0), CFG)
    assert (np.asarray(lb)[0, 0].tobytes()
            == np.asarray(la)[0, 0].tobytes())
    assert (np.asarray(lb)[1, 0].tobytes()
            == np.asarray(lc)[0, 0].tobytes())
    # Inactive rows (q_pos = -1) are finite, not NaN.
    linact, _, _ = step(PARAMS, toks, jnp.asarray([5, -1], jnp.int32),
                        kb, vb, CFG)
    assert bool(jnp.isfinite(linact).all())


# ---------------------------------------------------------------------------
# Engine: executables, bitwise acceptance, invariance, warm start
# ---------------------------------------------------------------------------

def test_engine_bitwise_vs_noncached_forward_through_executables():
    """Acceptance gate: the engine's paged, donated, AOT-compiled
    prefill/decode executables reproduce the non-incremental forward
    bitwise — captured logits compared position by position."""
    eng = make_engine()
    eng.warm_start()
    prompt = [3, 1, 4, 1, 5, 9, 2]
    N = 6
    req = eng.submit(prompt, max_new_tokens=N)
    rows, pf = [], []
    orig_dec, orig_pf = eng._decode_iteration, eng._prefill

    def wrapped_dec(active):
        logits = orig_dec(active)
        rows.append(logits[active[0][0]].copy())
        return logits

    def wrapped_pf(slot, r, prompt=None):
        out = orig_pf(slot, r, prompt)
        pf.append(out.copy())
        return out

    eng._decode_iteration = wrapped_dec
    eng._prefill = wrapped_pf
    eng.run_until_idle()
    gen = req.result(0)
    full = prompt + gen
    sf = jax.jit(serving_forward, static_argnums=(2, 3))
    ref = np.asarray(sf(PARAMS, jnp.asarray([full], jnp.int32), CFG,
                        eng.capacity))
    P = len(prompt)
    assert pf[0].tobytes() == ref[0, P - 1].tobytes()
    for i, row in enumerate(rows[:N - 1]):
        assert row.tobytes() == ref[0, P + i].tobytes(), f"decode {i}"


def test_engine_greedy_matches_reference_and_batch_invariance():
    eng = make_engine()
    eng.warm_start()
    prompts = [[5, 3, 8], [1, 2, 3, 4, 5, 6], [9, 9, 2, 6]]
    ref = [reference_rollout(p, 7, eng.capacity) for p in prompts]
    # Sequential, one at a time.
    seq_out = [eng.generate(list(p), max_new_tokens=7) for p in prompts]
    assert seq_out == ref
    # Concurrent: all three share the decode batch (3 slots); the
    # completions must be identical — batch-composition invariance.
    eng2 = make_engine()
    eng2.warm_start()
    reqs = [eng2.submit(list(p), max_new_tokens=7) for p in prompts]
    eng2.run_until_idle()
    assert [r.result(0) for r in reqs] == ref


@pytest.mark.parametrize("capacity", [32, 64])
def test_engine_capacity_finished_rollout_is_bitwise(capacity):
    """A CAPACITY-finished rollout (prompt + max_new_tokens over the
    KV capacity, no earlier EOS) must match the non-incremental
    forward bitwise — both schedulers produce the same tokens either
    way, so only a reference comparison can catch a boundary bug
    here.  The scheduler evicts the moment prompt+generated hits
    capacity, so the deepest decode runs at length == capacity-2 and
    writes [token, dummy] into the view's last two entries;
    forward_step staying exact at length == capacity-1 as well is
    gated by test_decode_at_final_capacity_position_is_bitwise.
    capacity == max_seq_len (64, the engine default) additionally
    exercises the decode block's final-position path end to end."""
    eng = make_engine(capacity=capacity)
    eng.warm_start()
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(7), (capacity - 4,), 0, CFG.vocab_size)]
    req = eng.submit(list(prompt), max_new_tokens=99)
    eng.run_until_idle()
    out = req.result(0)
    assert req.finish_reason == FinishReason.CAPACITY
    assert len(prompt) + len(out) == eng.capacity
    assert out == reference_rollout(prompt, len(out), eng.capacity)


def test_engine_eos_and_sampling_determinism():
    eng = make_engine()
    eng.warm_start()
    ref = reference_rollout([5, 3, 8], 12, eng.capacity)
    # EOS at the first reference token stops generation immediately.
    out = eng.generate([5, 3, 8], max_new_tokens=12, eos_id=ref[0])
    assert out == ref[:1]
    # Temperature sampling is deterministic given (seed, rid, step).
    a = eng.generate([5, 3, 8], max_new_tokens=6, temperature=0.8,
                     seed=11)
    eng3 = make_engine()
    eng3.warm_start()
    b = eng3.generate([5, 3, 8], max_new_tokens=6, temperature=0.8,
                      seed=11)
    assert a == b


def test_engine_one_dispatch_per_decode_iteration():
    """Megakernel-style contract, in two halves: a steady-state decode
    iteration invokes the donated decode executable EXACTLY once
    (gather → forward → scatter is one program), and issues ZERO eager
    XLA launches outside it (eager ops dispatch through the patched
    pjit path and would show up in the record scope; the AOT
    executable's own launch does not)."""
    from horovod_tpu.utils import xla_dispatch

    eng = make_engine()
    eng.warm_start()
    for p in ([1, 2, 3], [4, 5, 6, 7]):
        eng.submit(list(p), max_new_tokens=5)
    eng.step()  # admissions + prefills + decode
    calls = []
    compiled = eng._exec[("decode",)]
    eng._exec[("decode",)] = (
        lambda *a: (calls.append(1) or compiled(*a)))
    with xla_dispatch.exact_scope():
        with xla_dispatch.record(all_threads=True) as scope:
            eng.step()  # steady state: decode only
    assert len(calls) == 1, f"{len(calls)} decode executable calls"
    assert scope.count == 0, (
        f"{scope.count} eager dispatches leaked out of the decode "
        f"executable")
    eng._exec[("decode",)] = compiled
    eng.run_until_idle()


def test_engine_tensor_parallel_matches_single_device():
    from horovod_tpu.core.topology import make_mesh

    single = make_engine()
    single.warm_start()
    ref = single.generate([2, 7, 1, 8, 2, 8], max_new_tokens=8)
    mesh = make_mesh(data=1, model=2, devices=jax.devices()[:2])
    tp = make_engine(mesh=mesh)
    assert tp.cache.page_sharding() is not None
    tp.warm_start()
    out = tp.generate([2, 7, 1, 8, 2, 8], max_new_tokens=8)
    assert out == ref


def test_engine_warm_start_from_manifest(tmp_path, monkeypatch):
    """Relaunch: the manifest records the serving executables; a fresh
    engine's warm_start rebuilds them BEFORE any request arrives and
    flips readiness, and the rebuilt executables replay bitwise."""
    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    e1 = make_engine()
    e1.warm_start()
    out1 = e1.generate([1, 2, 3, 4, 5], max_new_tokens=6)
    man = json.loads(
        (tmp_path / "megakernel_manifest.json").read_text())
    kinds = {(e["kind"], e.get("bucket")) for e in man["entries"]
             if e["variant"] == "serving"}
    assert ("decode", None) in kinds and ("prefill", 8) in kinds

    e2 = make_engine()
    assert not e2.ready
    warmed = e2.warm_start(str(tmp_path))
    assert warmed >= 2 and e2.ready
    assert ("prefill", 8) in e2._exec  # present before any request
    assert e2.generate([1, 2, 3, 4, 5], max_new_tokens=6) == out1


def test_engine_foreign_manifest_entries_are_skipped(tmp_path):
    from horovod_tpu.ops import megakernel as mk

    entry = dict(make_engine()._manifest_identity())
    entry.update(kind="decode", bucket=None)
    entry["model"] = dict(entry["model"], d_model=999)
    mk.record_manifest_entry(entry, str(tmp_path))
    e = make_engine()
    assert e.warm_start(str(tmp_path)) == 0 and e.ready


def test_engine_serving_metrics_flow():
    import horovod_tpu.telemetry as telemetry

    eng = make_engine()
    eng.warm_start()
    before = telemetry.metrics().get("serving.tokens_generated",
                                     {}).get("value", 0)
    eng.generate([4, 4, 4], max_new_tokens=5)
    snap = telemetry.metrics()
    assert snap["serving.tokens_generated"]["value"] == before + 5
    assert snap["serving.ttft_seconds"]["count"] >= 1
    assert snap["serving.token_seconds"]["count"] >= 1


# ---------------------------------------------------------------------------
# HTTP front door on the shared exporter (route registry)
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_route_registry_dispatch_and_health_contributors():
    from horovod_tpu.telemetry import exporter as tel_exporter
    from horovod_tpu.telemetry.registry import MetricsRegistry

    routes = tel_exporter.routes()
    calls = []

    def handler(query, body):
        calls.append((query, body))
        return 200, b'{"pong": true}', "application/json"

    routes.register("/ping", handler, methods=("GET", "POST"))
    routes.register_health("unit", lambda: (False, {"why": "testing"}))
    exp = tel_exporter.start_exporter(MetricsRegistry(), 0,
                                      host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{exp.port}"
        status, body = _get(base + "/ping?x=1")
        assert status == 200 and body["pong"] is True
        assert calls[0][0] == "x=1"
        # A not-ready contributor makes /healthz NOT_READY with 503.
        try:
            _get(base + "/healthz")
            pytest.fail("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            payload = json.loads(e.read())
            assert payload["status"] == "NOT_READY"
            assert payload["unit"] == {"why": "testing"}
        routes.register_health("unit", lambda: (True, {"ok": 1}))
        status, payload = _get(base + "/healthz")
        assert status == 200 and payload["status"] == "ok"
    finally:
        exp.close()
        routes.unregister("/ping")
        routes.unregister_health("unit")


def test_lmserver_generate_http_and_readiness():
    """/healthz NOT_READY before warm start; /generate answers with the
    engine's exact completion plus latency fields; /metrics shares the
    same listener (route registry, not a second port)."""
    from horovod_tpu.telemetry import exporter as tel_exporter

    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=64)
    params = init_transformer(jax.random.PRNGKey(5), cfg)
    ref_engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                                 capacity=32)
    ref_engine.warm_start()
    prompt = list(b"hi")
    ref = ref_engine.generate(prompt, max_new_tokens=6)

    engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                             capacity=32)
    # Readiness before warm start: register health only, probe, then
    # start (LMServer.start warm-starts synchronously).
    routes = tel_exporter.routes()
    routes.register_health("serving", engine.health)
    exp = tel_exporter.start_exporter(
        __import__("horovod_tpu.telemetry", fromlist=["x"]).registry(),
        0, host="127.0.0.1")
    base = f"http://127.0.0.1:{exp.port}"
    try:
        try:
            _get(base + "/healthz")
            pytest.fail("expected NOT_READY before warm_start")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["serving"]["ready"] is False

        with LMServer(engine) as srv:
            srv.start()
            status, health = _get(base + "/healthz")
            assert status == 200 and health["serving"]["ready"] is True
            status, resp = _post(base + "/generate",
                                 {"text": "hi", "max_tokens": 6})
            assert status == 200
            assert resp["tokens"] == ref
            assert resp["finish_reason"] == "max_new_tokens"
            assert resp["ttft_ms"] is not None and resp["total_ms"] > 0
            assert isinstance(resp.get("text"), str)
            # Token-id prompts hit the same path.
            status, resp2 = _post(base + "/generate",
                                  {"tokens": prompt, "max_tokens": 6})
            assert resp2["tokens"] == ref
            # Error paths: bad JSON / no prompt / out-of-vocab ids.
            for payload in ({}, {"tokens": [999999]},):
                try:
                    _post(base + "/generate", payload)
                    pytest.fail("expected 400")
                except urllib.error.HTTPError as e:
                    assert e.code == 400
            # Drained admission is a retryable 503, not a client 400.
            engine.scheduler.drain()
            try:
                _post(base + "/generate", {"tokens": prompt})
                pytest.fail("expected 503 while draining")
            except urllib.error.HTTPError as e:
                assert e.code == 503
            engine.scheduler.resume()
            status, resp3 = _post(base + "/generate",
                                  {"tokens": prompt, "max_tokens": 6})
            assert resp3["tokens"] == ref
            # /metrics still served by the same listener.
            status, snap = _get(base + "/metrics?format=json")
            assert status == 200
            assert "serving.tokens_generated" in snap
    finally:
        exp.close()
        routes.unregister_health("serving")


def test_engine_abort_all_fails_everything_and_reopens():
    """abort_all (the serve loop's recovery): every queued AND
    in-flight request is failed with finish_reason='error' and done
    set, the KV pages are recycled, and admission re-opens — the
    returned list is exactly what the drain removed, so a submission
    racing the recovery is failed fast instead of silently lost."""
    eng = make_engine()
    eng.warm_start()
    reqs = [eng.submit([i + 1, 2, 3], max_new_tokens=8)
            for i in range(4)]  # 3 slots -> one stays queued
    eng.step()
    assert eng.scheduler.occupancy() == 3
    failed = eng.abort_all()
    assert {r.rid for r in failed} == {r.rid for r in reqs}
    for r in reqs:
        assert r.done.is_set() and r.finish_reason == FinishReason.ERROR
    assert eng.cache.free_pages() == eng.cache.n_pages - 1
    assert eng.generate([1, 2], max_new_tokens=2)  # admission re-open


def test_follow_applies_abort_marker_and_abort_all_broadcasts_it():
    """Multi-host recovery: abort_all broadcasts an abort marker, and a
    follower receiving it (here scripted as the post-prefill sync of a
    step that died on rank 0) frees its whole cache mirror — without
    this the fleet's caches diverge after a poisoned step and every
    later decode breaks the bitwise contract."""
    eng = make_engine()
    msgs = [{"stop": False, "admit": [(0, [1, 2, 3])]}, {"abort": True}]
    eng._bcast = lambda obj: msgs.pop(0)
    assert eng.follow() is True
    assert msgs == [] and eng.cache.length(0) < 0
    assert eng.cache.free_pages() == eng.cache.n_pages - 1
    # Rank-0 side: abort_all under a live control plane broadcasts the
    # marker so blocked followers unblock into the same recovery.
    eng2 = make_engine()
    sent = []
    eng2._multiprocess = lambda: True
    eng2._bcast = lambda obj: sent.append(obj)
    eng2.submit([1, 2, 3], max_new_tokens=4)
    eng2.abort_all()
    assert {"abort": True} in sent


def test_lmserver_survives_engine_exception_and_keeps_serving():
    """Error recovery: one poisoned step fails every caught-up request
    FAST as an HTTP 500 with finish_reason='error' (not 'drained', not
    a timeout, not a 200 masquerading as success), frees the KV slots,
    and the server keeps serving new requests — slot 0 must be reusable
    (regression: a recovery that drained only the scheduler left the
    cache slots mapped and bricked admission)."""
    engine = make_engine()
    with LMServer(engine, port=0) as srv:
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        boom = {"armed": True}
        orig = engine._decode_iteration

        def poisoned(active):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("injected decode failure")
            return orig(active)

        engine._decode_iteration = poisoned
        try:
            _post(base + "/generate",
                  {"tokens": [1, 2, 3], "max_tokens": 6,
                   "timeout": 30})
            pytest.fail("expected HTTP 500 for the failed request")
        except urllib.error.HTTPError as e:
            assert e.code == 500
            resp = json.loads(e.read())
        assert resp["finish_reason"] == "error", resp
        # The server is healthy again: same slot serves a new request.
        status, resp2 = _post(base + "/generate",
                              {"tokens": [1, 2, 3], "max_tokens": 6})
        assert status == 200
        assert resp2["finish_reason"] == "max_new_tokens"
        ref = make_engine()
        ref.warm_start()
        assert resp2["tokens"] == ref.generate([1, 2, 3],
                                               max_new_tokens=6)


def test_lmserver_midflight_drain_returns_retryable_503():
    """An elastic drain evicting an in-flight request must surface to
    its blocked /generate handler as a retryable 503 with the partial
    tokens and finish_reason='drained' — never a 200 that only
    finish_reason distinguishes from success (the docs/inference.md
    failure-status contract)."""
    engine = make_engine()
    with LMServer(engine, port=0) as srv:
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        orig = engine._decode_iteration

        def draining(active):
            engine._decode_iteration = orig
            engine.drain()  # mid-flight eviction, continuation exported

        engine._decode_iteration = draining
        try:
            _post(base + "/generate",
                  {"tokens": [1, 2, 3], "max_tokens": 6, "timeout": 30})
            pytest.fail("expected HTTP 503 for the drained request")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            resp = json.loads(e.read())
        assert resp["finish_reason"] == "drained", resp
        # Resume (the relaunch path) and the same server serves again.
        engine.import_requests([])
        status, resp2 = _post(base + "/generate",
                              {"tokens": [1, 2, 3], "max_tokens": 6})
        assert status == 200
        assert resp2["finish_reason"] == "max_new_tokens"


def test_lmserver_client_disconnect_releases_slot(monkeypatch):
    """hvd-chaos satellite (ISSUE 9): a client that vanishes
    mid-generation is detected by the handler's ClientProbe (the
    serving.disconnect injection site), the slot is released through
    the abort path, serving.client_disconnects counts it, and the SAME
    slot serves the next request normally."""
    import horovod_tpu.chaos as chaos
    import horovod_tpu.telemetry as tel

    engine = make_engine(max_slots=1)
    with LMServer(engine, port=0) as srv:
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        before = tel.metrics().get("serving.client_disconnects",
                                   {}).get("value", 0)
        monkeypatch.setenv("HVD_TPU_FAULTS",
                           "serving.disconnect:count=1@7")
        chaos.reload()
        try:
            try:
                _post(base + "/generate",
                      {"tokens": [1, 2, 3], "max_tokens": 400,
                       "timeout": 30})
                pytest.fail("expected HTTP 499 for the gone client")
            except urllib.error.HTTPError as e:
                assert e.code == 499
                resp = json.loads(e.read())
            assert "disconnected" in resp["error"]
        finally:
            monkeypatch.delenv("HVD_TPU_FAULTS", raising=False)
            chaos.reload()
        after = tel.metrics().get("serving.client_disconnects",
                                  {}).get("value", 0)
        assert after - before >= 1
        # The slot was released at the loop boundary: the one-slot
        # engine admits (and completes) a fresh request.
        status, resp2 = _post(base + "/generate",
                              {"tokens": [1, 2, 3], "max_tokens": 6,
                               "timeout": 30})
        assert status == 200
        assert resp2["finish_reason"] == "max_new_tokens"
        deadline = _time_monotonic_deadline(5.0)
        while engine.scheduler.occupancy() and not deadline():
            pass
        assert engine.scheduler.occupancy() == 0


def _time_monotonic_deadline(seconds):
    import time as _t

    end = _t.monotonic() + seconds
    return lambda: _t.monotonic() > end


def test_lmserver_concurrent_http_requests():
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=64)
    params = init_transformer(jax.random.PRNGKey(5), cfg)
    engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                             capacity=32)
    with LMServer(engine, port=0) as srv:
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        results = {}

        def hit(i):
            results[i] = _post(base + "/generate",
                               {"tokens": [i + 1, 2, 3],
                                "max_tokens": 5})[1]["tokens"]

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        ref_engine = InferenceEngine(params, cfg, max_slots=2,
                                     page_size=8, capacity=32)
        ref_engine.warm_start()
        for i in range(4):
            assert results[i] == ref_engine.generate(
                [i + 1, 2, 3], max_new_tokens=5), i


# ---------------------------------------------------------------------------
# Elastic drain / resume
# ---------------------------------------------------------------------------

def test_elastic_serving_state_drain_commit_resume(tmp_path, monkeypatch):
    """Fleet resize: drain mid-generation, commit, 'relaunch' a fresh
    engine, resume — completions equal the uninterrupted run exactly
    (greedy continuations ride the bitwise contract)."""
    from horovod_tpu import elastic

    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5], [2, 7, 1, 8]]
    e0 = make_engine()
    e0.warm_start()
    ref = [e0.generate(list(p), max_new_tokens=10) for p in prompts]

    e1 = make_engine()
    e1.warm_start()
    for p in prompts:
        e1.submit(list(p), max_new_tokens=10)
    state = elastic.ServingState(e1)
    for _ in range(4):  # some in flight, queue possibly nonempty
        e1.step()
    exported = state.drain_commit()
    assert state.wait_committed()
    assert len(exported) == 3
    assert any(x["generated_prefix"] for x in exported)  # mid-flight

    e2 = make_engine()
    e2.warm_start()
    state2 = elastic.ServingState(e2)
    state2.sync()  # loads the disk commit and resubmits
    pend = e2.scheduler.pending()
    assert len(pend) == 3
    e2.run_until_idle()
    results = sorted(tuple(r.result(0)) for r in pend)
    assert results == sorted(map(tuple, ref))


def test_engine_drain_with_nothing_in_flight_is_empty():
    eng = make_engine()
    eng.warm_start()
    assert eng.drain() == []
    eng.import_requests([])  # resume with nothing
    assert eng.generate([1, 2], max_new_tokens=2)  # still serves


def test_import_requests_attaches_prefix_before_admissible():
    """A relaunched continuation's generated_prefix must be on the
    Request BEFORE it enters the queue: a live serve loop can admit
    and sample it immediately, and the sampling rng keys on
    len(prefix) + len(generated) — a late prefix assignment would draw
    from the wrong rng position and break continuation determinism."""
    eng = make_engine()
    eng.warm_start()
    seen = []
    orig_submit = eng.scheduler.submit

    def spy(req):
        seen.append(list(req.prefix))
        return orig_submit(req)

    eng.scheduler.submit = spy
    eng.import_requests([{"prompt": [1, 2, 3, 9],
                          "generated_prefix": [9],
                          "max_new_tokens": 4, "seed": 1,
                          "temperature": 0.7}])
    assert seen == [[9]]


def test_import_requests_skips_unresumable_continuations():
    """A resize can SHRINK capacity; a drained continuation whose
    prompt no longer fits must be skipped (flight-recorder event), not
    abort the import loop and silently drop the rest of the committed
    export behind it."""
    eng = make_engine()  # capacity 64
    oversized = {"prompt": list(range(eng.capacity + 4)),
                 "generated_prefix": [], "max_new_tokens": 8}
    ok = {"prompt": [1, 2, 3], "generated_prefix": [9],
          "max_new_tokens": 4}
    out = eng.import_requests([oversized, ok])
    assert len(out) == 1 and out[0].prompt == [1, 2, 3]
    assert out[0].prefix == [9]


def test_engine_drain_finishes_pending_requests_fast():
    """engine.drain() must also finish queued-but-unadmitted requests
    (finish_reason='drained', done set): the relaunch resubmits NEW
    Request objects from the export, so a /generate handler blocked on
    the original would otherwise hang to its client timeout instead of
    failing fast as a retryable 503."""
    eng = make_engine()
    eng.warm_start()
    reqs = [eng.submit([i + 1, 2, 3], max_new_tokens=8)
            for i in range(4)]  # 3 slots -> one stays queued
    eng.step()
    exported = eng.drain()
    assert len(exported) == 4  # pending still exported for relaunch
    for r in reqs:
        assert r.done.is_set(), r.rid
        assert r.finish_reason == FinishReason.DRAINED


def test_engine_abort_all_survives_dead_control_plane():
    """A control-plane fault that poisoned the step must not also kill
    the recovery: abort_all's abort broadcast failing is swallowed and
    the LOCAL drain/fail/reopen still completes."""
    eng = make_engine()
    eng.warm_start()
    eng._multiprocess = lambda: True

    def dead_bcast(obj):
        raise ConnectionError("control plane down")

    eng._bcast = dead_bcast
    req = eng.submit([1, 2, 3], max_new_tokens=4)
    failed = eng.abort_all()
    assert req in failed and req.finish_reason == FinishReason.ERROR
    eng._multiprocess = lambda: False
    assert eng.generate([1, 2], max_new_tokens=2)  # admission re-open


def test_engine_warm_start_none_keeps_chosen_manifest_dir(tmp_path):
    """warm_start(None) after warm_start(dir) must keep recording to
    dir (a later default-argument call — e.g. LMServer.start() with no
    warm_start_dir — must not silently revert to the env default)."""
    eng = make_engine()
    eng.warm_start(str(tmp_path))
    eng.warm_start()
    assert eng._manifest_dir == str(tmp_path)
    eng.generate([1, 2, 3], max_new_tokens=2)
    man = json.loads((tmp_path / "megakernel_manifest.json").read_text())
    assert any(e["variant"] == "serving" for e in man["entries"])


# ---------------------------------------------------------------------------
# Serving checkpoint export/load
# ---------------------------------------------------------------------------

def test_serving_checkpoint_roundtrip(tmp_path):
    from horovod_tpu.utils.checkpoint import (load_serving_checkpoint,
                                              save_serving_checkpoint)

    save_serving_checkpoint(str(tmp_path), PARAMS, CFG, block=True)
    params, cfg, meta = load_serving_checkpoint(str(tmp_path))
    assert cfg.vocab_size == CFG.vocab_size
    assert cfg.n_layers == CFG.n_layers
    assert meta["tokenizer"]["kind"] == "byte"
    same = all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(PARAMS),
                        jax.tree_util.tree_leaves(params)))
    assert same
    # And the loaded checkpoint actually serves.
    eng = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                          capacity=32)
    eng.warm_start()
    ref_eng = make_engine()
    ref_eng.warm_start()
    assert (eng.generate([1, 2, 3], max_new_tokens=4)
            == ref_eng.generate([1, 2, 3], max_new_tokens=4))


# ---------------------------------------------------------------------------
# Shared-prefix cache at the engine level (hvd-spec)
# ---------------------------------------------------------------------------

def test_engine_prefix_hit_is_bitwise_and_saves_prefill():
    """The tentpole gate: a prompt extending a cached prefix maps the
    shared pages copy-free, prefills ONLY the suffix, and the
    completion is bitwise-equal to the cache-off engine's (and the
    non-incremental reference)."""
    from horovod_tpu import telemetry as _telemetry

    def counter(name):
        return _telemetry.metrics().get(name, {}).get("value", 0)

    header = list(range(1, 18))  # 17 tokens -> 2 full pages published
    ext = header + [40, 41, 42]
    # Ground truth: the non-incremental reference — a cache-off engine
    # equals it by the standing contract (and bench.py's prefix_cache
    # leg gates cache-on vs cache-off completions directly).
    a_off = reference_rollout(header, 5, 32)
    b_off = reference_rollout(ext, 5, 32)

    on = make_engine(prefix_cache=True)
    on.warm_start()
    assert on.generate(list(header), max_new_tokens=5) == a_off
    assert on.cache.prefix_stats()["cached_pages"] == 2
    hits0 = counter("serving.prefix_hits")
    pages0 = counter("serving.prefix_pages_shared")
    # Capture the suffix prefill's width: with 16 tokens shared, the
    # 4-token suffix rides the 4-bucket, not the 32-bucket.
    widths = []
    orig = on._prefill_exec

    def spy(bucket, draft=False):
        widths.append(bucket)
        return orig(bucket, draft)

    on._prefill_exec = spy
    assert on.generate(list(ext), max_new_tokens=5) == b_off
    on._prefill_exec = orig
    assert counter("serving.prefix_hits") - hits0 == 1
    assert counter("serving.prefix_pages_shared") - pages0 == 2
    assert max(widths) <= 4  # 20-token prompt, 16 shared -> suffix 4


def test_engine_prefix_refcounts_follow_slot_lifecycle():
    eng = make_engine(prefix_cache=True)
    eng.warm_start()
    header = list(range(1, 18))
    eng.generate(list(header), max_new_tokens=3)
    assert eng.cache.prefix_stats()["referenced_pages"] == 0
    req = eng.submit(header + [50], max_new_tokens=30)
    eng.step()  # admitted: the shared pages are referenced
    assert eng.cache.prefix_stats()["referenced_pages"] == 2
    eng.run_until_idle()
    req.result(0)
    stats = eng.cache.prefix_stats()
    assert stats["referenced_pages"] == 0
    assert stats["cached_pages"] >= 2
    assert eng.cache.free_pages() == eng.cache.total_pages


def test_engine_prefix_cache_off_env(monkeypatch):
    monkeypatch.setenv("HVD_TPU_PREFIX_CACHE", "0")
    eng = make_engine()
    assert not eng.cache.prefix_enabled
    monkeypatch.delenv("HVD_TPU_PREFIX_CACHE")
    assert make_engine().cache.prefix_enabled


def test_engine_seed_prefixes_rebuilds_bitwise_pages():
    """seed_prefixes (the elastic rebuild path) produces pages a later
    request hits copy-free — and the hit is bitwise-identical to a
    cold engine's completion."""
    header = list(range(1, 17))  # exactly 2 pages
    ref = reference_rollout(header + [7, 8], 6, 32)

    eng = make_engine(prefix_cache=True)
    eng.warm_start()
    assert eng.seed_prefixes([header]) == 2
    assert eng.cache.prefix_stats()["cached_pages"] == 2
    assert eng.generate(header + [7, 8], max_new_tokens=6) == ref
    # Seeding an already-covered chain is a no-op.
    assert eng.seed_prefixes([header]) == 0


def test_scheduler_admit_defers_on_page_budget():
    """Admission headroom (hvd-spec satellite): a head-of-queue request
    whose prefill does not fit the page budget defers — strictly FIFO
    (nothing behind it admits first), the slot is not burned, and
    serving.admission_deferred counts it."""
    from horovod_tpu import telemetry as _telemetry

    def deferred():
        return _telemetry.metrics().get(
            "serving.admission_deferred", {}).get("value", 0)

    s = ContinuousBatchingScheduler(max_slots=2, capacity=64)
    big = s.submit(_req(prompt=list(range(40))))     # 5 pages @ 8
    small = s.submit(_req(prompt=[1, 2, 3]))         # 1 page
    need = {big.rid: 5, small.rid: 1}
    before = deferred()
    admitted = s.admit(page_budget=4,
                       pages_needed=lambda r: need[r.rid])
    assert admitted == []                 # head blocked => FIFO holds
    assert deferred() - before == 1
    assert s.queue_depth() == 2
    # With headroom back, the original order admits.
    admitted = s.admit(page_budget=8,
                       pages_needed=lambda r: need[r.rid])
    assert [r.rid for _, r in admitted] == [big.rid, small.rid]


# ---------------------------------------------------------------------------
# Elastic: prefix-index export/rebuild roundtrip
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_elastic_serving_state_prefix_roundtrip(tmp_path, monkeypatch):
    """drain_commit exports the prefix index next to the
    continuations; a relaunched fleet's sync() rebuilds the shared
    pages (ghost prefills), so the FIRST post-relaunch request already
    hits copy-free — and everything stays bitwise.  (slow: four warm
    engines; the CI serving-bench job runs it unfiltered — tier-1
    keeps the cheap seed_prefixes leg.)"""
    from horovod_tpu import elastic
    from horovod_tpu import telemetry as _telemetry

    def hits():
        return _telemetry.metrics().get(
            "serving.prefix_hits", {}).get("value", 0)

    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    header = list(range(1, 18))  # 2 full pages published
    eng = make_engine(prefix_cache=True)
    eng.warm_start()
    ref_a = eng.generate(list(header), max_new_tokens=4)
    assert eng.cache.prefix_stats()["cached_pages"] == 2
    state = elastic.ServingState(eng)
    mid = eng.submit(header + [60], max_new_tokens=6)
    exported = state.drain_commit()
    assert state.wait_committed()
    assert exported and mid.finish_reason == FinishReason.DRAINED

    fresh = make_engine(prefix_cache=True)
    fresh.warm_start()
    state2 = elastic.ServingState(fresh)
    state2.sync()  # rebuilds pages AND resubmits the continuation
    assert fresh.cache.prefix_stats()["cached_pages"] >= 2
    pend = fresh.scheduler.pending()
    assert len(pend) == 1
    fresh.run_until_idle()
    # The continuation finished exactly as the uninterrupted run.
    uninterrupted = make_engine(prefix_cache=False)
    uninterrupted.warm_start()
    assert pend[0].result(0) == uninterrupted.generate(
        header + [60], max_new_tokens=6)
    # Replaying the original header is a copy-free hit, bitwise.
    h0 = hits()
    assert fresh.generate(list(header), max_new_tokens=4) == ref_a
    assert hits() > h0
