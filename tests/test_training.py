"""Data-parallel training correctness.

The gold test: an N-replica data-parallel step must produce the SAME
updated parameters as a single-device step on the full concatenated batch
(gradient averaging over shards == gradient over the union).  This is the
semantic contract behind the reference's DistributedOptimizer
(tensorflow/__init__.py:170-192) and its loss-parity examples."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.parallel.training import (make_train_step, make_eval_step,
                                           shard_batch)


def _loss_fn_factory(model):
    def loss_fn(params, batch):
        images, labels = batch
        logits = model.apply({"params": params}, images)
        return cross_entropy_loss(logits, labels)
    return loss_fn


def test_dp_step_matches_single_device(hvd):
    """Distributed step == single-device step on the full batch."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn_factory(model)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)

    images, labels = synthetic_mnist(64)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    # Single-device reference step.
    def single_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    p_ref, _, loss_ref = jax.jit(single_step)(params, opt_state, batch)

    # Distributed step over 8 replicas.
    step = make_train_step(loss_fn, opt, donate=False)
    p_dp, _, loss_dp = step(params, opt.init(params), shard_batch(batch))

    np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_dp),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6)


def test_training_loss_decreases(hvd):
    """A few distributed steps fit the synthetic labels (examples-as-tests,
    ≙ the reference CI's shrunken MNIST runs, .travis.yml:105-109)."""
    model = MnistMLP(hidden=64)
    params = init_params(model)
    loss_fn = _loss_fn_factory(model)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt)

    images, labels = synthetic_mnist(256)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_fusion_threshold_does_not_change_results(hvd):
    """Bucketed vs unbucketed gradient reduction must be numerically
    equivalent (fusion is an optimization, not a semantic change —
    docs/tensor-fusion.md)."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn_factory(model)
    opt = optax.sgd(0.1)

    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    outs = []
    for threshold in (0, 1 << 26):
        step = make_train_step(loss_fn, opt, fusion_threshold=threshold,
                               donate=False)
        p, _, _ = step(params, opt.init(params), batch)
        outs.append(p)
    for a, b in zip(jax.tree_util.tree_leaves(outs[0]),
                    jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_eval_step_metric_average(hvd):
    model = MnistMLP(hidden=32)
    params = init_params(model)

    def metric_fn(params, batch):
        images, labels = batch
        logits = model.apply({"params": params}, images)
        return cross_entropy_loss(logits, labels)

    images, labels = synthetic_mnist(64)
    ev = make_eval_step(metric_fn)
    m = ev(params, shard_batch((jnp.asarray(images), jnp.asarray(labels))))
    assert np.isfinite(float(m))


def test_distributed_optimizer_inside_step(hvd):
    """DistributedOptimizer passed straight to make_train_step is honored
    (unwrap + in-context reduction)."""
    import horovod_tpu as hvd_api

    model = MnistMLP(hidden=16)
    params = init_params(model)
    loss_fn = _loss_fn_factory(model)
    dopt = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    step = make_train_step(loss_fn, dopt, donate=False)
    images, labels = synthetic_mnist(32)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    p, _, loss = step(params, dopt.init(params), batch)
    assert np.isfinite(float(loss))


def test_distributed_optimizer_jit_misuse_raises(hvd):
    """Tracing the eager optimizer path inside jit (outside shard_map) is a
    clear error, not silent corruption."""
    import horovod_tpu as hvd_api

    dopt = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    params = {"w": jnp.ones(4)}
    st = dopt.init(params)

    @jax.jit
    def bad_step(g, st, p):
        return dopt.update(g, st, p)

    with pytest.raises(Exception) as ei:
        bad_step({"w": jnp.ones(4)}, st, params)
    assert "replica context" in str(ei.value)


def test_adasum_step_matches_ladder_reference(hvd):
    """op=Adasum in the compiled step: the whole-gradient combination
    must equal the pairwise recursive-doubling spec applied to the
    per-shard gradients (computed independently here), and one SGD
    update with that combination must reproduce the step's params."""
    import horovod_tpu as H

    n = H.size()
    w_true = jnp.array([1.0, -2.0, 0.5])
    X = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (8 * n, 3)),
                   np.float32)
    y = X @ np.asarray(w_true)

    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean((xb @ params["w"] - yb) ** 2)

    params = {"w": jnp.zeros((3,))}
    lr = 0.05
    opt = optax.sgd(lr)
    step = make_train_step(loss_fn, opt, op=H.Adasum, donate=False)
    p1, _, _ = step(params, opt.init(params),
                    shard_batch((jnp.asarray(X), jnp.asarray(y))))

    # Reference: per-shard gradients (contiguous leading-axis chunks,
    # shard_batch's layout) + the pairwise adasum spec.
    def ref_adasum(vs):
        vs = [np.asarray(v, np.float64) for v in vs]
        while len(vs) > 1:
            vs = [((1 - (a @ b) / (2 * (a @ a))) * a
                   + (1 - (a @ b) / (2 * (b @ b))) * b)
                  for a, b in zip(vs[0::2], vs[1::2])]
        return vs[0]

    g_fn = jax.grad(loss_fn)
    k = len(X) // n
    shard_grads = [np.asarray(
        g_fn(params, (jnp.asarray(X[i * k:(i + 1) * k]),
                      jnp.asarray(y[i * k:(i + 1) * k])))["w"])
        for i in range(n)]
    want = np.asarray(params["w"]) - lr * ref_adasum(shard_grads)
    np.testing.assert_allclose(np.asarray(p1["w"]), want, rtol=1e-4,
                               atol=1e-6)


def test_adasum_training_converges(hvd):
    """A short op=Adasum training run reaches a small loss (the combiner
    is scale-insensitive, not a plain mean — convergence is the contract,
    not identical trajectories)."""
    import horovod_tpu as H

    model = MnistMLP(hidden=16)
    params = init_params(model)
    loss_fn = _loss_fn_factory(model)
    opt = H.DistributedOptimizer(optax.sgd(0.2), op=H.Adasum)
    step = make_train_step(loss_fn, opt, donate=False)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    opt_state = opt.init(params)
    first = last = None
    # Adasum of correlated shard gradients combines to roughly ONE
    # shard's magnitude (scale-insensitivity is the point), so progress
    # per step resembles single-replica SGD — budget steps accordingly.
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, batch)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.65, (first, last)


def test_adasum_rejects_sparse_and_bad_ops(hvd):
    import horovod_tpu as H
    from horovod_tpu.parallel.data import DistributedOptimizer

    with pytest.raises(ValueError, match="Average/Sum/Adasum"):
        DistributedOptimizer(optax.sgd(0.1), op=H.Max)
