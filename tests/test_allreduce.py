"""Allreduce tests — self-verifying collectives over a dtype × dims matrix
(≙ reference test/test_tensorflow.py:34-97, test/test_torch.py:26-166)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

DTYPES = [jnp.uint8, jnp.int8, jnp.int32, jnp.int64, jnp.float32,
          jnp.bfloat16]
DIMS = [1, 2, 3]


def _per_replica_tensor(size, dim, dtype, seed=0):
    """Each replica contributes a distinct tensor (rank r → value r+1)."""
    rng = np.random.RandomState(seed)
    base = rng.randint(1, 4, size=(17,) * dim).astype(np.float64)
    stack = np.stack([(base * (r + 1)) for r in range(size)])
    return jnp.asarray(stack).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("dim", DIMS)
def test_allreduce_per_replica(hvd, dtype, dim):
    """Sum across replicas with distinct per-replica values
    (≙ test_horovod_allreduce, test_tensorflow.py:34-63)."""
    size = hvd.size()
    stack = _per_replica_tensor(size, dim, dtype)
    x = hvd.shard(stack)
    y = hvd.allreduce(x, average=False)
    expected = np.asarray(stack.astype(jnp.float64)).sum(axis=0)
    got = np.asarray(y.astype(jnp.float64))
    assert got.shape == stack.shape
    for r in range(size):
        np.testing.assert_allclose(got[r], expected, rtol=1e-2)


def test_allreduce_replicated_value(hvd):
    """A plain array is every replica's identical contribution → x * size."""
    x = jnp.arange(12.0, dtype=jnp.float32).reshape(3, 4)
    y = hvd.allreduce(x, average=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * hvd.size(),
                               rtol=1e-6)


def test_allreduce_average(hvd):
    size = hvd.size()
    stack = jnp.stack([jnp.full((5,), float(r), jnp.float32)
                       for r in range(size)])
    y = hvd.allreduce(hvd.shard(stack), average=True)
    expected = np.mean(np.arange(size, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(y)[0], np.full((5,), expected),
                               rtol=1e-6)


def test_allreduce_average_integer_floor(hvd):
    """Integer average floors, matching the reference's in-place integer
    divide (torch/tensor_util.h DivideTensorInPlace)."""
    size = hvd.size()
    stack = jnp.stack([jnp.full((3,), r, jnp.int32) for r in range(size)])
    y = hvd.allreduce(hvd.shard(stack), average=True)
    expected = sum(range(size)) // size
    assert np.asarray(y)[0].tolist() == [expected] * 3


def test_allreduce_async_fused(hvd):
    """Many async allreduces before any synchronize: exercises the fusion
    path and asserts poll() returned False at least once, i.e. the API is
    genuinely asynchronous (≙ test_horovod_allreduce_async_fused,
    test_torch.py:124-166)."""
    size = hvd.size()
    tensors = [jnp.full((50, 50), float(i), jnp.float32) for i in range(20)]
    handles = [hvd.allreduce_async(t, average=False, name=f"fuse.{i}")
               for i, t in enumerate(tensors)]
    seen_not_ready = any(not hvd.poll(h) for h in handles)
    results = [hvd.synchronize(h) for h in handles]
    for i, r in enumerate(results):
        np.testing.assert_allclose(np.asarray(r),
                                   np.full((50, 50), i * size), rtol=1e-6)
    # Async-ness: with 20 queued ops at least one poll should have preceded
    # execution.  (Kept as a soft signal exactly like the reference, which
    # asserts it only for large tensor counts.)
    assert seen_not_ready or size == 1


def test_allreduce_shape_mismatch_raises(hvd):
    """Cross-replica shape mismatch → validation error on every replica
    (≙ test_horovod_allreduce_error, test_tensorflow.py:233-258)."""
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    # Build two half-sized per-replica groups with conflicting shapes under
    # the same tensor name by submitting raw requests through the queue.
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    # Private coordinator: the shared one is drained by the background
    # tick thread, which would race these direct injections.
    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "mismatch.shape"
    for r in range(hvd.size()):
        shape = (2, 3) if r % 2 == 0 else (3, 2)
        coord.submit(Request(r, RequestType.ALLREDUCE,
                             DataType.FLOAT32, name, -1, -1, shape))
    resps = coord.poll_responses({name: 24})
    assert len(resps) == 1
    assert resps[0].response_type.name == "ERROR"
    assert "Mismatched allreduce tensor shapes" in resps[0].error_message


def test_allreduce_dtype_mismatch_raises(hvd):
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "mismatch.dtype"
    for r in range(hvd.size()):
        dt = DataType.FLOAT32 if r % 2 == 0 else DataType.INT32
        coord.submit(Request(r, RequestType.ALLREDUCE, dt, name,
                             -1, -1, (3,)))
    resps = coord.poll_responses({name: 12})
    assert resps[0].response_type.name == "ERROR"
    assert "Mismatched data types" in resps[0].error_message


def test_mismatched_operations_raise(hvd):
    """One replica allreduces while another allgathers the same name
    (≙ mpi_ops mismatch tests, test_tensorflow.py:259-305)."""
    if hvd.size() < 2:
        pytest.skip("needs >1 replica")
    from horovod_tpu.ops.coordinator import PyCoordinator
    from horovod_tpu.ops.wire import Request, RequestType, DataType

    coord = PyCoordinator(hvd.size(), 64 << 20)
    name = "mismatch.op"
    for r in range(hvd.size()):
        op = RequestType.ALLREDUCE if r % 2 == 0 else RequestType.ALLGATHER
        coord.submit(Request(r, op, DataType.FLOAT32, name,
                             -1, -1, (3,)))
    resps = coord.poll_responses({name: 12})
    assert resps[0].response_type.name == "ERROR"
    assert "Mismatched collective operations" in resps[0].error_message


def test_allreduce_scalar(hvd):
    """Rank-0 (scalar) tensors allreduce fine — the reference injects a
    dummy dimension for these (torch/adapter.cc:64-73); XLA needs no such
    workaround."""
    y = hvd.allreduce(jnp.float32(2.5), average=False)
    np.testing.assert_allclose(float(y), 2.5 * hvd.size(), rtol=1e-6)


def test_grouped_allreduce_values_and_order(hvd):
    """Grouped entry point (≙ post-v0.13 hvd.grouped_allreduce): one
    result per tensor, input order preserved, fused under the hood."""
    tensors = [jnp.full((i + 1,), float(i + 1)) for i in range(4)]
    outs = hvd.grouped_allreduce(tensors, average=False)
    assert len(outs) == 4
    for i, out in enumerate(outs):
        assert out.shape == (i + 1,)
        np.testing.assert_allclose(np.asarray(out), (i + 1.0) * hvd.size())
    outs = hvd.grouped_allreduce(tensors, average=True)
    for i, out in enumerate(outs):
        np.testing.assert_allclose(np.asarray(out), i + 1.0)


def test_grouped_allreduce_async_handles(hvd):
    hs = hvd.grouped_allreduce_async(
        [jnp.ones((2,)), jnp.full((3,), 2.0)], average=False)
    assert len(hs) == 2
    a, b = (hvd.synchronize(h) for h in hs)
    np.testing.assert_allclose(np.asarray(a), float(hvd.size()))
    np.testing.assert_allclose(np.asarray(b), 2.0 * hvd.size())


def test_grouped_allreduce_torch_frontend(hvd):
    torch = pytest.importorskip("torch")
    import horovod_tpu.frontends.torch as thvd

    ts = [torch.full((2,), 1.0), torch.full((3,), 3.0)]
    outs = thvd.grouped_allreduce(ts, average=True)
    np.testing.assert_allclose(outs[0].numpy(), 1.0)
    np.testing.assert_allclose(outs[1].numpy(), 3.0)
    # In-place grouped variant writes back into the callers' tensors.
    thvd.grouped_allreduce_(ts, average=True)
    np.testing.assert_allclose(ts[0].numpy(), 1.0)
    np.testing.assert_allclose(ts[1].numpy(), 3.0)


def test_grouped_allreduce_overlapping_anonymous_groups(hvd):
    """Two anonymous groups in flight at once must not collide on names
    (the default base is unique per call)."""
    h1 = hvd.grouped_allreduce_async([jnp.ones((2,))], average=False)
    h2 = hvd.grouped_allreduce_async([jnp.full((2,), 2.0)], average=False)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h1[0])),
                               float(hvd.size()))
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h2[0])),
                               2.0 * hvd.size())


# -- Reduce operators (post-v0.13 hvd op= API; v0.13 hard-codes MPI_SUM
# -- + the average divide) --------------------------------------------------

def test_allreduce_op_min_max_product(hvd):
    """Min/Max/Product over genuinely different per-replica values."""
    n = hvd.size()
    vals = jnp.arange(1.0, n + 1.0).reshape(n, 1)
    x = hvd.shard(vals)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Min))[0], 1.0)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Max))[0], float(n))
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Product))[0],
        float(np.prod(np.arange(1.0, n + 1.0))))
    # Integer dtypes work for min/max/product (no divide involved).
    xi = hvd.shard(jnp.arange(1, n + 1, dtype=jnp.int32).reshape(n, 1))
    assert int(np.asarray(hvd.allreduce(xi, op=hvd.Max))[0]) == n


def test_allreduce_op_replicated_semantics(hvd):
    """A replicated input means every replica contributes the same value:
    sum gives x*n, product x**n, min/max/adasum give x back."""
    n = hvd.size()
    x = jnp.array([2.0])
    assert float(hvd.allreduce(x, op=hvd.Sum)[0]) == 2.0 * n
    assert float(hvd.allreduce(x, op=hvd.Product)[0]) == 2.0 ** n
    assert float(hvd.allreduce(x, op=hvd.Min)[0]) == 2.0
    assert float(hvd.allreduce(x, op=hvd.Max)[0]) == 2.0
    assert float(hvd.allreduce(x, op=hvd.Adasum)[0]) == pytest.approx(2.0)


def _adasum_reference(vectors):
    """Recursive-doubling Adasum in numpy (arXiv:2006.02924): the
    executable spec the ppermute ladder must match."""
    vs = [np.asarray(v, np.float32).ravel().astype(np.float64)
          for v in vectors]
    while len(vs) > 1:
        nxt = []
        for a, b in zip(vs[0::2], vs[1::2]):
            dot, na, nb = a @ b, a @ a, b @ b
            ca = 1.0 - (dot / (2.0 * na) if na > 0 else 0.0)
            cb = 1.0 - (dot / (2.0 * nb) if nb > 0 else 0.0)
            nxt.append(ca * a + cb * b)
        vs = nxt
    return vs[0]


def test_allreduce_op_adasum_matches_reference(hvd):
    """The ppermute ladder equals the pairwise recursive-doubling spec,
    including orthogonal contributions (where adasum = plain sum)."""
    n = hvd.size()
    rng = np.random.RandomState(7)
    vals = rng.normal(size=(n, 6)).astype(np.float32)
    out = np.asarray(hvd.allreduce(hvd.shard(jnp.asarray(vals)),
                                   op=hvd.Adasum))
    want = _adasum_reference(list(vals))
    np.testing.assert_allclose(out[0], want, rtol=1e-5)
    # Orthogonal vectors: dots vanish, adasum degenerates to the sum.
    eye = np.eye(n, dtype=np.float32)
    out = np.asarray(hvd.allreduce(hvd.shard(jnp.asarray(eye)),
                                   op=hvd.Adasum))
    np.testing.assert_allclose(out[0], np.ones(n), rtol=1e-6)


def test_allreduce_op_adasum_vhdd_matches_reference(hvd):
    """Vectors past the dispatch threshold (2n elements) take the
    bandwidth-optimal VHDD kernel (~2|v| wire vs the ladder's
    log2(n)|v|, ops/collective.py _adasum_vhdd); it computes the same
    recursive pairwise tree as the spec.  103 % 8 != 0 exercises the
    pad-to-n path, and orthogonal contributions still degenerate to the
    plain sum."""
    n = hvd.size()
    rng = np.random.RandomState(11)
    vals = rng.normal(size=(n, 103)).astype(np.float32)
    out = np.asarray(hvd.allreduce(hvd.shard(jnp.asarray(vals)),
                                   op=hvd.Adasum, name="vhdd.big"))
    want = _adasum_reference(list(vals))
    np.testing.assert_allclose(out[0], want, rtol=1e-4, atol=1e-5)
    eye = np.eye(n, 4 * n, dtype=np.float32)  # orthogonal, size 4n > 2n
    out = np.asarray(hvd.allreduce(hvd.shard(jnp.asarray(eye)),
                                   op=hvd.Adasum, name="vhdd.orth"))
    np.testing.assert_allclose(out[0], eye.sum(0), rtol=1e-6, atol=1e-6)


def test_allreduce_op_argument_validation(hvd):
    with pytest.raises(ValueError, match="not both"):
        hvd.allreduce(jnp.ones((2,)), average=True, op=hvd.Sum)
    with pytest.raises(ValueError, match="floating-point"):
        hvd.allreduce(jnp.ones((2,), jnp.int32), op=hvd.Adasum)
    with pytest.raises(ValueError, match="sum/average"):
        from horovod_tpu import IndexedSlices
        sl = IndexedSlices(jnp.ones((1, 2)), jnp.array([0]), (2, 2))
        hvd.allreduce(sl, op=hvd.Max)


def test_adasum_requires_power_of_two(hvd):
    """A 3-replica mesh cannot run the recursive-doubling ladder."""
    import horovod_tpu as hvd3
    import jax
    hvd3.init(devices=jax.devices()[:3])
    try:
        with pytest.raises(ValueError, match="power-of-two"):
            hvd3.allreduce(jnp.ones((2,)), op=hvd3.Adasum)
    finally:
        hvd3.init(devices=jax.devices())  # restore for the fixture


def test_grouped_allreduce_op_kwarg(hvd):
    """The grouped API takes op= too; a max group reduces element-max."""
    n = hvd.size()
    ts = [hvd.shard(jnp.arange(float(n)).reshape(n, 1)),
          hvd.shard(jnp.arange(float(n), 0.0, -1.0).reshape(n, 1))]
    outs = hvd.grouped_allreduce(ts, op=hvd.Max)
    np.testing.assert_allclose(np.asarray(outs[0])[0], float(n - 1))
    np.testing.assert_allclose(np.asarray(outs[1])[0], float(n))
