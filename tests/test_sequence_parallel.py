"""Sequence-parallel attention tests: ring + Ulysses vs dense attention.

Self-verifying in the reference's style (SURVEY.md §4): the sharded
computation must reproduce the single-device result over the gathered
sequence, forward and backward.
"""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import SEQ_AXIS, make_mesh
from horovod_tpu.ops.flash_attention import mha_reference
from horovod_tpu.parallel.sequence import ring_attention, ulysses_attention

TOL = 5e-5
SPEC = P(None, None, SEQ_AXIS)


def _qkv(b=2, h=4, s=256, d=32, seed=1):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d)) for k in ks)


def _sharded(fn, mesh):
    return jax.jit(_compat.shard_map(fn, mesh=mesh, in_specs=SPEC,
                                 out_specs=SPEC, check_vma=False))


@pytest.mark.parametrize("ring_size", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(ring_size, causal):
    mesh = make_mesh(seq=ring_size, devices=jax.devices()[:ring_size])
    q, k, v = _qkv()

    sm = _sharded(
        lambda q, k, v: ring_attention(q, k, v, causal=causal, block_q=32,
                                       block_k=32), mesh)
    o = sm(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(o - ref)) < TOL


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients(causal):
    mesh = make_mesh(seq=4, devices=jax.devices()[:4])
    q, k, v = _qkv(s=128, d=16)
    w = jnp.sin(jnp.arange(16))

    sm = _sharded(
        lambda q, k, v: ring_attention(q, k, v, causal=causal, block_q=32,
                                       block_k=32), mesh)
    got = jax.grad(lambda q, k, v: jnp.sum(sm(q, k, v) * w),
                   (0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=causal) * w),
        (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = make_mesh(seq=4, devices=jax.devices()[:4])
    q, k, v = _qkv()

    sm = _sharded(
        lambda q, k, v: ulysses_attention(q, k, v, causal=causal,
                                          block_q=32, block_k=32), mesh)
    o = sm(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(o - ref)) < TOL


def test_ulysses_gradients():
    mesh = make_mesh(seq=4, devices=jax.devices()[:4])
    q, k, v = _qkv(s=128, d=16)
    w = jnp.sin(jnp.arange(16))

    sm = _sharded(
        lambda q, k, v: ulysses_attention(q, k, v, causal=True, block_q=32,
                                          block_k=32), mesh)
    got = jax.grad(lambda q, k, v: jnp.sum(sm(q, k, v) * w),
                   (0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) * w),
        (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh(seq=4, devices=jax.devices()[:4])
    q, k, v = _qkv(h=3)
    sm = _sharded(lambda q, k, v: ulysses_attention(q, k, v), mesh)
    with pytest.raises(ValueError, match="divisible"):
        sm(q, k, v)


def test_ring_attention_composes_with_data_parallel():
    # 2-D mesh: batch over 'data', sequence ring over 'seq'.
    mesh = make_mesh(data=2, seq=4)
    q, k, v = _qkv(b=4, s=128)

    spec = P("data", None, SEQ_AXIS)
    sm = jax.jit(_compat.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True, block_q=32,
                                       block_k=32),
        mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False))
    o = sm(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.max(jnp.abs(o - ref)) < TOL
