"""Tests for the hvd-analyze subsystem (horovod_tpu/analysis/).

One test group per pass, each seeding a violation and asserting the
exact named diagnostic:

* lint — each rule catches a seeded violation, the waiver comment works,
  and (the acceptance gate) the shipped tree itself is clean;
* program — compare_signatures flags every divergence kind with the
  exact reference-style label, the coordinator-side tracker converts a
  reordered request stream into an immediate diagnostic, and
  verify_program round-trips single-process;
* lockorder — a seeded A→B / B→A inversion raises, consistent orders
  and RLock reentrancy do not, and the factories honor
  HVD_TPU_LOCK_CHECK;
* races — a two-thread unguarded write on a ``# guarded_by:`` field
  raises DataRaceError naming field, lock, and both threads; the same
  interleaving under the annotated lock is silent;
* threads — a cross-role call is a static thread-role finding (cleared
  by a handoff marker) and a stamped thread entering another role's
  method raises ThreadRoleError;
* donation — a post-donation read is a static finding (cleared by the
  rebind idiom), and re-dispatching a donated buffer raises
  DonationError naming the ORIGINAL executable, argument, and site;
* analyze_sources — the cross-pass driver also audits waivers: a
  ``# lint: ok(...)`` suppressing nothing is itself a stale-waiver
  finding, and the shipped tree is clean under ALL passes.
"""

import os
import threading
import textwrap

import numpy as np
import pytest

import horovod_tpu.analysis as hvd_analysis
from horovod_tpu.analysis import donation
from horovod_tpu.analysis import lint as L
from horovod_tpu.analysis import lockorder
from horovod_tpu.analysis import program as prog
from horovod_tpu.analysis import races
from horovod_tpu.analysis import threads as troles
from horovod_tpu.ops import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "horovod_tpu")


def _lint(src: str):
    return L.lint_sources({"seed.py": textwrap.dedent(src)})


# ---------------------------------------------------------------------------
# lint: guarded-by
# ---------------------------------------------------------------------------

GUARDED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []  # guarded_by: _lock

        def good(self):
            with self._lock:
                self.items.append(1)

        def also_good_locked(self):
            self.items.append(2)
"""


def test_guarded_by_clean_when_locked():
    assert _lint(GUARDED_CLASS) == []


def test_guarded_by_breach_is_caught():
    findings = _lint(GUARDED_CLASS + """
        def bad(self):
            return len(self.items)
""")
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "guarded-by"
    assert "Box.items" in f.message and "_lock" in f.message


def test_guarded_by_dataclass_field_and_producer_typing():
    findings = _lint("""
        import threading
        from dataclasses import dataclass, field

        @dataclass
        class _GS:
            # guarded_by: lock
            registry: dict = field(default_factory=dict)
            lock: object = None

        _gs = _GS()

        def global_state() -> _GS:
            return _gs

        def good():
            st = global_state()
            with st.lock:
                return len(st.registry)

        def bad():
            st = global_state()
            return len(st.registry)

        def bad_module_var():
            return _gs.registry
    """)
    assert [f.rule for f in findings] == ["guarded-by", "guarded-by"]
    assert {"bad", "bad_module_var"} == {
        f.message.split("(in ")[1].rstrip(")") for f in findings}


def test_guarded_by_waiver_comment():
    findings = _lint(GUARDED_CLASS + """
        def waived(self):
            return len(self.items)  # lint: ok(snapshot for debug dump)
""")
    assert findings == []


# ---------------------------------------------------------------------------
# lint: blocking-under-lock
# ---------------------------------------------------------------------------

def test_blocking_call_under_lock_is_caught():
    findings = _lint("""
        import threading
        import time

        _lock = threading.Lock()

        def bad():
            with _lock:
                time.sleep(1.0)

        def fine():
            time.sleep(1.0)
            with _lock:
                pass
    """)
    assert len(findings) == 1
    assert findings[0].rule == "blocking-under-lock"
    assert "sleep" in findings[0].message


def test_socket_recv_under_lock_is_caught():
    findings = _lint("""
        import threading

        _lock = threading.Lock()

        def bad(sock):
            with _lock:
                return sock.recv(4)
    """)
    assert [f.rule for f in findings] == ["blocking-under-lock"]


# ---------------------------------------------------------------------------
# lint: rank-conditioned-collective
# ---------------------------------------------------------------------------

def test_rank_conditioned_collective_is_caught():
    findings = _lint("""
        from horovod_tpu import allreduce, rank

        def bad(x):
            if rank() == 0:
                return allreduce(x)
            return x

        def fine(x):
            if rank() == 0:
                print("root")
            return allreduce(x)
    """)
    assert len(findings) == 1
    assert findings[0].rule == "rank-conditioned-collective"
    assert "allreduce" in findings[0].message


def test_rank_conditioned_else_branch_is_caught():
    findings = _lint("""
        from horovod_tpu import broadcast, local_rank

        def bad(x):
            if local_rank() != 0:
                pass
            else:
                return broadcast(x, 0)
    """)
    assert [f.rule for f in findings] == ["rank-conditioned-collective"]


# ---------------------------------------------------------------------------
# lint: the shipped tree is clean (the CI --strict gate)
# ---------------------------------------------------------------------------

def test_shipped_tree_has_no_findings():
    findings = L.lint_paths([PKG])
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# program: compare_signatures divergence kinds
# ---------------------------------------------------------------------------

def _entry(seq=0, op="allreduce", name="x", dtype="float32", shape=(2,),
           red="SUM", ps=0, source=""):
    return prog.SignatureEntry(seq, op, name, dtype, tuple(shape), red,
                               ps, source)


def test_compare_identical_programs_ok():
    p = [_entry(0), _entry(1, name="y")]
    assert prog.compare_signatures({0: list(p), 1: list(p)}) is None


def test_compare_dtype_divergence():
    msg = prog.compare_signatures({
        0: [_entry()], 1: [_entry(dtype="int32")]})
    assert "Mismatched data types" in msg
    assert "entry #0" in msg
    assert "rank 0" in msg and "rank 1" in msg
    assert "float32" in msg and "int32" in msg


def test_compare_shape_divergence():
    msg = prog.compare_signatures({
        0: [_entry(shape=(2,))], 1: [_entry(shape=(3,))]})
    assert "Mismatched tensor shapes" in msg


def test_compare_allgather_ragged_dim0_is_legal():
    sigs = {0: [_entry(op="allgather", red="", shape=(1, 4))],
            1: [_entry(op="allgather", red="", shape=(3, 4))]}
    assert prog.compare_signatures(sigs) is None
    sigs[1] = [_entry(op="allgather", red="", shape=(3, 5))]
    assert "Mismatched tensor shapes" in prog.compare_signatures(sigs)


def test_compare_op_and_reduce_op_divergence():
    assert "Mismatched collective operations" in prog.compare_signatures(
        {0: [_entry()], 1: [_entry(op="broadcast", red="")]})
    assert "Mismatched reduce operations" in prog.compare_signatures(
        {0: [_entry(red="SUM")], 1: [_entry(red="MIN")]})


def test_compare_order_divergence():
    msg = prog.compare_signatures({
        0: [_entry(0, name="a"), _entry(1, name="b")],
        1: [_entry(0, name="b"), _entry(1, name="a")]})
    assert "Mismatched tensor names" in msg
    assert "rank-divergent program order" in msg


def test_compare_count_divergence():
    msg = prog.compare_signatures({
        0: [_entry(0)],
        1: [_entry(0), _entry(1, name="extra")]})
    assert "Rank-divergent collective count" in msg
    assert "rank 0 recorded 1" in msg and "rank 1 recorded 2" in msg
    assert "extra" in msg  # the first unmatched entry is named


def test_compare_process_set_cycle():
    """X in set 1 before Y in set 2 on rank 0, the swap on rank 1: each
    set's coordinator sees a consistent stream, so only the wait-for
    cycle check can catch it."""
    x0, y0 = _entry(0, name="x", ps=1), _entry(1, name="y", ps=2)
    y1, x1 = _entry(0, name="y", ps=2), _entry(1, name="x", ps=1)
    msg = prog.compare_signatures({0: [x0, y0], 1: [y1, x1]})
    assert "Potential process-set deadlock cycle" in msg
    assert "1 -> 2 -> 1" in msg
    assert "deadlock" in msg


def test_compare_offset_windows_align_by_seq():
    """Bounded windows that slid by different amounts (one rank traced
    an extra op before both overflowed) must pair entries by ABSOLUTE
    seq: the overlap here agrees entry-for-entry, so only the count
    divergence is reported — not a bogus name mismatch from
    positionally zipping offset lists."""
    # rank 0 window: seqs 10..14 of ops a10..a14; rank 1 traced one
    # extra early op, so its window holds seqs 11..15 = a10..a14 at
    # seq+1 plus nothing new — i.e. the same logical tail.
    win0 = [_entry(s, name=f"op.{s}") for s in range(10, 15)]
    win1 = [_entry(s, name=f"op.{s}") for s in range(11, 15)]
    msg = prog.compare_signatures({0: win0, 1: win1},
                                  totals={0: 15, 1: 16})
    assert "Rank-divergent collective count" in msg
    assert "Mismatched tensor names" not in msg


def test_cross_validate_digest_fast_path():
    p = [_entry(0), _entry(1, name="y")]
    a = prog.pack_program(0, p, 2)
    b = prog.pack_program(1, p, 2)
    assert prog.cross_validate({0: a, 1: b}) is None
    c = prog.pack_program(1, [p[0], _entry(1, name="z")], 2)
    assert "Mismatched tensor names" in prog.cross_validate({0: a, 1: c})


# ---------------------------------------------------------------------------
# program: coordinator-side tracker + facade hook
# ---------------------------------------------------------------------------

def _req(rank, name, dtype=wire.DataType.FLOAT32, shape=(2,),
         rt=wire.RequestType.ALLREDUCE):
    return wire.Request(request_rank=rank, request_type=rt,
                        tensor_type=dtype, tensor_name=name,
                        tensor_shape=tuple(shape),
                        reduce_op=wire.ReduceOp.SUM)


def test_program_tracker_flags_reordered_streams():
    t = prog.ProgramTracker(2)
    assert t.feed(_req(0, "a")) is None
    assert t.feed(_req(0, "b")) is None
    diag = t.feed(_req(1, "b"))  # rank 1's entry #0 vs rank 0's "a"
    assert diag is not None and "Mismatched tensor names" in diag
    assert "'a'" in diag and "'b'" in diag


def test_program_tracker_trims_matching_prefix():
    t = prog.ProgramTracker(2)
    for i in range(100):
        assert t.feed(_req(0, f"op.{i}")) is None
        assert t.feed(_req(1, f"op.{i}")) is None
    # The cross-checked prefix is dropped; memory stays O(skew).
    assert t._base == 100
    assert all(len(s) == 0 for s in t._streams)


def test_program_tracker_disabled_by_join():
    """hvd.join() legalizes rank-divergent programs: a JOIN request must
    disarm the tracker so a rejoining rank is never positionally
    compared against entries peers issued during its absence."""
    t = prog.ProgramTracker(2)
    assert t.feed(_req(0, "epoch1.g8")) is None
    assert t.feed(_req(0, "epoch1.g9")) is None  # rank 1 ran out of data
    join = wire.Request(request_rank=1,
                        request_type=wire.RequestType.JOIN,
                        tensor_type=wire.DataType.UINT8,
                        tensor_name="hvd.join")
    assert t.feed(join) is None
    # Rank 1 resumes next epoch at a different absolute position: no
    # false divergence on the healthy uneven workload.
    assert t.feed(_req(1, "epoch2.g0")) is None
    assert t.feed(_req(0, "epoch2.g0")) is None


def test_program_tracker_window_cap_disables():
    """An idle peer pins the prefix trim; the tracker disarms at the
    window bound instead of growing one entry per collective forever."""
    t = prog.ProgramTracker(2, window=10)
    for i in range(12):
        assert t.feed(_req(0, f"op.{i}")) is None
    assert t._disabled
    assert all(len(s) == 0 for s in t._streams)
    assert t.feed(_req(1, "late")) is None  # no comparisons once disarmed


def test_coordinator_program_check_emits_error_response(monkeypatch):
    monkeypatch.setenv("HVD_TPU_VERIFY_PROGRAM", "1")
    from horovod_tpu.ops.coordinator import Coordinator

    coord = Coordinator(size=2, fusion_threshold=1 << 20)
    coord.submit(_req(0, "a"))
    coord.submit(_req(1, "b"))
    resps = coord.poll_responses({})
    errs = [r for r in resps
            if r.response_type == wire.ResponseType.ERROR]
    assert errs, resps
    assert "Mismatched tensor names" in errs[0].error_message
    coord.close()


def test_verify_program_single_process(hvd2):
    import jax.numpy as jnp

    prog.recorder().clear()
    hvd2.allreduce(jnp.ones((3,)), average=False, name="vp.op")
    rep = hvd2.verify_program()
    assert rep.ranks == 1
    assert rep.entries == 1
    assert len(rep.digest) == 64
    # reset=True cleared the recorder for the next phase.
    assert prog.recorder().total() == 0


def test_recorder_captures_signature_fields(hvd2):
    import jax.numpy as jnp

    prog.recorder().clear()
    hvd2.allreduce(jnp.ones((4,), jnp.float32), average=False,
                   name="cap.op")
    entries = prog.recorder().entries()
    assert len(entries) == 1
    e = entries[0]
    assert (e.op, e.name, e.dtype, e.process_set_id) == (
        "allreduce", "cap.op", "float32", 0)
    assert e.reduce_op == wire.reduce_op_name(wire.ReduceOp.SUM)
    prog.recorder().clear()


def test_collective_source_tagging(hvd2):
    import jax.numpy as jnp

    prog.recorder().clear()
    with prog.collective_source("torch"):
        hvd2.allreduce(jnp.ones((2,)), average=False, name="tag.op")
    assert prog.recorder().entries()[0].source == "torch"
    prog.recorder().clear()


# ---------------------------------------------------------------------------
# lockorder
# ---------------------------------------------------------------------------

def test_lock_inversion_raises():
    a = lockorder.CheckedLock("inv.A")
    b = lockorder.CheckedLock("inv.B")

    def establish():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join()
    with b:
        with pytest.raises(lockorder.LockOrderError) as ei:
            a.acquire()
    assert "inv.A" in str(ei.value) and "inv.B" in str(ei.value)
    assert "inversion" in str(ei.value)


def test_consistent_order_is_fine():
    a = lockorder.CheckedLock("ok.A")
    b = lockorder.CheckedLock("ok.B")

    def use():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=use) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with a:
        with b:
            pass  # same order everywhere: no cycle, no raise


def test_rlock_reentrancy_is_not_an_inversion():
    r = lockorder.CheckedRLock("re.R")
    other = lockorder.CheckedLock("re.other")
    with r:
        with other:
            with r:  # reentrant acquisition adds no reverse edge
                pass


def test_three_lock_cycle_detected():
    a = lockorder.CheckedLock("tri.A")
    b = lockorder.CheckedLock("tri.B")
    c = lockorder.CheckedLock("tri.C")

    def order(x, y):
        with x:
            with y:
                pass

    for x, y in ((a, b), (b, c)):
        t = threading.Thread(target=order, args=(x, y))
        t.start()
        t.join()
    with c:
        with pytest.raises(lockorder.LockOrderError):
            a.acquire()


def test_factories_honor_env(monkeypatch):
    monkeypatch.setenv("HVD_TPU_LOCK_CHECK", "1")
    assert isinstance(lockorder.make_lock("env.t"),
                      lockorder.CheckedLock)
    assert isinstance(lockorder.make_rlock("env.tr"),
                      lockorder.CheckedRLock)
    monkeypatch.setenv("HVD_TPU_LOCK_CHECK", "0")
    assert isinstance(lockorder.make_lock("env.t2"), type(threading.Lock()))


def test_trylock_failure_does_not_corrupt_stack():
    a = lockorder.CheckedLock("try.A")
    a.acquire()

    def contend():
        assert a.acquire(blocking=False) is False

    t = threading.Thread(target=contend)
    t.start()
    t.join()
    a.release()
    # The failed try-acquire released its bookkeeping: reacquire works.
    with a:
        pass


# ---------------------------------------------------------------------------
# races: runtime lockset detector (HVD_TPU_RACE_CHECK=1)
# ---------------------------------------------------------------------------

def test_data_race_unguarded_cross_thread_write_raises(monkeypatch):
    """Seeded violation: a second thread writes a ``# guarded_by:``
    field with no lock held.  The named diagnostic carries the
    class.field, the annotated lock, and both threads."""
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "1")

    @races.race_checked
    class RaceBox:
        def __init__(self):
            self._lock = lockorder.CheckedLock("race.test.RaceBox._lock")
            self.val = 0  # guarded_by: _lock

    box = RaceBox()   # first-touch thread: the test's main thread
    box.val = 1       # still exclusive to the owner — silent
    errs = []

    def bump():
        try:
            box.val = 2   # no lock held: write-shares the field
        except races.DataRaceError as e:
            errs.append(e)

    t = threading.Thread(target=bump, name="race-bumper")
    t.start()
    t.join()
    assert errs, "unguarded cross-thread write must raise DataRaceError"
    msg = str(errs[0])
    assert "data race on RaceBox.val" in msg
    assert "'_lock'" in msg
    assert "'race-bumper'" in msg
    assert "no lock in common" in msg
    # The field is quarantined after the report, not stuck mid-machine.
    assert races.states_of(box)["val"] == 3  # _REPORTED


def test_locked_cross_thread_access_is_clean(monkeypatch):
    """The same interleaving under the annotated lock is silent and
    lands in shared-modified with a live candidate lockset."""
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "1")

    @races.race_checked
    class CleanBox:
        def __init__(self):
            self._lock = lockorder.CheckedLock("race.test.CleanBox._lock")
            self.val = 0  # guarded_by: _lock

    box = CleanBox()

    def bump():
        with box._lock:
            box.val += 1

    t = threading.Thread(target=bump, name="clean-bumper")
    t.start()
    t.join()
    with box._lock:
        box.val += 1
        assert box.val == 2
    assert races.states_of(box)["val"] == 2  # _SHARED_MOD, no race


def test_read_sharing_needs_no_lock(monkeypatch):
    """Concurrent READS never race: the field parks in the read-shared
    state even with an empty lockset (Eraser's read-share rule)."""
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "1")

    @races.race_checked
    class ReadBox:
        def __init__(self):
            self._lock = lockorder.CheckedLock("race.test.ReadBox._lock")
            self.val = 41  # guarded_by: _lock

    box = ReadBox()
    seen = []

    def peek():
        seen.append(box.val)

    t = threading.Thread(target=peek, name="reader")
    t.start()
    t.join()
    assert seen == [41]
    assert races.states_of(box)["val"] == 1  # _SHARED


def test_race_checked_is_noop_when_disarmed(monkeypatch):
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "0")

    @races.race_checked
    class PlainBox:
        def __init__(self):
            self.val = 0  # guarded_by: _lock

    assert not isinstance(PlainBox.__dict__.get("val"),
                          races._TrackedField)
    box = PlainBox()
    box.val = 7  # no descriptors, no tracking
    assert races.states_of(box) == {}


# ---------------------------------------------------------------------------
# threads: role contracts — static pass + dynamic asserts
# ---------------------------------------------------------------------------

THREADED_SRC = """
    class Pump:
        def rx_loop(self):  # thread: rx
            self.flush()

        def flush(self):  # thread: writer
            pass
"""


def test_thread_role_cross_role_call_is_caught():
    findings = troles.check_sources(
        {"seed.py": textwrap.dedent(THREADED_SRC)})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "thread-role"
    assert "rx_loop()" in f.message and "flush()" in f.message
    assert "'rx'" in f.message
    assert "# thread: writer" in f.message
    assert "handoff" in f.message


def test_thread_role_handoff_marker_clears_the_finding():
    src = textwrap.dedent("""
        class Pump:
            def rx_loop(self):  # thread: rx
                self.q.put(self.flush)  # thread: handoff(writer queue)
                self.flush()  # lint: ok(draining inline at shutdown)

            def flush(self):  # thread: writer
                pass
    """)
    assert troles.check_sources({"seed.py": src}) == []


def test_thread_role_same_role_call_is_fine():
    src = textwrap.dedent("""
        class Pump:
            def rx_loop(self):  # thread: rx
                self.on_frame()

            def on_frame(self):  # thread: rx
                pass
    """)
    assert troles.check_sources({"seed.py": src}) == []


def test_thread_role_require_raises_across_roles(monkeypatch):
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "1")
    errs = []

    def run():
        troles.set_role("rx")
        try:
            troles.require("serve-loop", "Engine.abort_all")
        except troles.ThreadRoleError as e:
            errs.append(e)

    t = threading.Thread(target=run, name="rx-thread")
    t.start()
    t.join()
    assert errs, "a stamped thread entering another role must raise"
    msg = str(errs[0])
    assert "Engine.abort_all" in msg
    assert "# thread: serve-loop" in msg
    assert "'rx'" in msg and "'rx-thread'" in msg


def test_thread_role_unstamped_and_matching_pass(monkeypatch):
    monkeypatch.setenv("HVD_TPU_RACE_CHECK", "1")
    # The test's main thread is unstamped: user threads drive any API.
    troles.require("serve-loop", "Engine.abort_all")
    ok = []

    def run():
        troles.set_role("serve-loop")
        troles.require("serve-loop", "Engine.abort_all")
        ok.append(True)

    t = threading.Thread(target=run, name="serve-loop-thread")
    t.start()
    t.join()
    assert ok == [True]


# ---------------------------------------------------------------------------
# donation: static post-donation-read rule + runtime sanitizer
# ---------------------------------------------------------------------------

DONATING_FN = """
    import jax

    def train(update, params, batch):
        step = jax.jit(update, donate_argnums=(0,))
        new_params = step(params, batch)
        return params, new_params
"""


def test_post_donation_read_is_caught():
    findings = donation.check_sources(
        {"seed.py": textwrap.dedent(DONATING_FN)})
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "post-donation-read"
    assert "'params'" in f.message
    assert "step()" in f.message
    assert "position 0" in f.message
    assert "return value" in f.message


def test_post_donation_rebind_idiom_is_clean():
    src = textwrap.dedent("""
        import jax

        def train(update, params, batch):
            step = jax.jit(update, donate_argnums=(0,))
            params = step(params, batch)
            return params
    """)
    assert donation.check_sources({"seed.py": src}) == []


def test_post_donation_read_waiver():
    src = textwrap.dedent("""
        import jax

        def train(update, params, batch):
            step = jax.jit(update, donate_argnums=(0,))
            out = step(params, batch)
            return params  # lint: ok(cpu-backend test keeps the ref)
    """)
    assert donation.check_sources({"seed.py": src}) == []


def test_guard_dispatch_names_the_original_donation(monkeypatch):
    """Runtime seeded violation: dispatching the same buffer through a
    donating executable twice raises DonationError naming the FIRST
    donation's executable, argument index, and site."""
    monkeypatch.setenv("HVD_TPU_DONATION_CHECK", "1")
    donation.reset()
    try:
        buf = np.ones((4,), np.float32)
        keep = np.zeros((4,), np.float32)
        out = donation.guard_dispatch(
            "serving/decode/b2", lambda a, b: a + b, (buf, keep), (0,))
        np.testing.assert_allclose(out, 1.0)
        with pytest.raises(donation.DonationError) as ei:
            donation.guard_dispatch(
                "serving/decode/b2", lambda a, b: a + b, (buf, keep),
                (0,))
        msg = str(ei.value)
        assert "use-after-donation" in msg
        assert "'serving/decode/b2'" in msg
        assert "argument 0" in msg
        assert "donated at [" in msg
        assert "RETURN value" in msg
    finally:
        donation.reset()


def test_donation_check_probe_and_poisoned_buffer(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DONATION_CHECK", "1")
    donation.reset()
    try:
        buf = np.arange(3.0)
        donation.register(buf, "mk/group0", 2)
        with pytest.raises(donation.DonationError) as ei:
            donation.check(buf)
        assert "'mk/group0'" in str(ei.value)
        assert "argument 2" in str(ei.value)

        poisoned = donation.PoisonedBuffer(
            "pipeline/stage1/jit_b", 0, "pipeline.py:100(dispatch)")
        with pytest.raises(donation.DonationError) as ei2:
            _ = poisoned.shape
        msg = str(ei2.value)
        assert "'pipeline/stage1/jit_b'" in msg
        assert "attribute read ('shape')" in msg
        assert "pipeline.py:100" in msg
    finally:
        donation.reset()


def test_guard_dispatch_disarmed_is_plain_call(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DONATION_CHECK", "0")
    donation.reset()
    buf = np.ones((2,))
    donation.guard_dispatch("x", lambda a: a * 2, (buf,), (0,))
    out = donation.guard_dispatch("x", lambda a: a * 2, (buf,), (0,))
    np.testing.assert_allclose(out, 2.0)  # no registry, no raise


# ---------------------------------------------------------------------------
# analyze_sources: cross-pass driver + stale-waiver audit
# ---------------------------------------------------------------------------

def test_stale_waiver_is_a_finding():
    findings = hvd_analysis.analyze_sources({"seed.py": textwrap.dedent("""
        def f():
            return 1  # lint: ok(left over from a deleted rule)
    """)})
    assert [f.rule for f in findings] == ["stale-waiver"]
    assert "left over from a deleted rule" in findings[0].message
    assert "suppresses nothing" in findings[0].message


def test_used_waiver_is_not_stale():
    findings = hvd_analysis.analyze_sources({"seed.py": textwrap.dedent(
        GUARDED_CLASS + """
        def waived(self):
            return len(self.items)  # lint: ok(snapshot for debug dump)
""")})
    assert findings == []


def test_analyze_sources_merges_every_pass():
    """One source seeding a lint breach, a cross-role call, a
    post-donation read, and a stale waiver: the driver reports all
    four rules, sorted."""
    src = textwrap.dedent("""
        import threading
        import jax

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded_by: _lock

            def bad(self):
                return len(self.items)

            def rx_loop(self):  # thread: rx
                self.flush()

            def flush(self):  # thread: writer
                pass

        def train(update, params, batch):
            step = jax.jit(update, donate_argnums=(0,))
            out = step(params, batch)
            return params

        def clean():
            return 2  # lint: ok(nothing fires here)
    """)
    findings = hvd_analysis.analyze_sources({"seed.py": src})
    assert sorted(f.rule for f in findings) == [
        "guarded-by", "post-donation-read", "stale-waiver", "thread-role"]


def test_all_passes_shipped_tree_clean():
    """The PR's acceptance gate: lint + thread-role +
    post-donation-read + stale-waiver over the shipped package — zero
    findings (what CI's `python -m horovod_tpu.analysis --strict`
    enforces)."""
    findings = hvd_analysis.analyze_paths([PKG])
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_strict_exit_codes(tmp_path, capsys):
    from horovod_tpu.analysis import main

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading
        import time

        _lock = threading.Lock()

        def f():
            with _lock:
                time.sleep(1)
    """))
    assert main([str(bad)]) == 0          # advisory without --strict
    assert main(["--strict", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "blocking-under-lock" in out
    assert main(["--strict", os.path.join(PKG, "analysis")]) == 0
