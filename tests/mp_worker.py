"""Worker script for the multi-process tests (launched by horovod_tpu.run).

Each scenario prints a marker line on success; tests/test_multiprocess.py
asserts on the merged rank-prefixed output.  This is the TPU translation of
the reference's ``mpirun -np 2 pytest`` CI leg (.travis.yml:96-123): real
separate processes, real cross-process negotiation.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def scenario_basic(hvd):
    import jax.numpy as jnp

    rank = hvd.rank()
    assert hvd.size() == 2, hvd.size()
    assert rank == int(os.environ["HVD_TPU_PROCESS_ID"])
    assert hvd.local_size() == 2  # both processes on this host
    assert hvd.local_rank() == rank
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0

    # Allreduce: sum and average of genuinely different contributions.
    out = hvd.allreduce(jnp.array([float(rank + 1)] * 4), average=False)
    np.testing.assert_allclose(np.asarray(out), 3.0)
    out = hvd.allreduce(jnp.array([float(rank + 1)] * 4), average=True)
    np.testing.assert_allclose(np.asarray(out), 1.5)

    # Ragged allgather: dim 0 differs per rank (MPI_Allgatherv case).
    mine = jnp.full((rank + 1, 2), float(rank), jnp.float32)
    out = np.asarray(hvd.allgather(mine))
    assert out.shape == (3, 2), out.shape
    np.testing.assert_allclose(out[:1], 0.0)
    np.testing.assert_allclose(out[1:], 1.0)

    # Broadcast from a non-zero root.
    out = hvd.broadcast(jnp.array([float(rank)] * 3), root_rank=1)
    np.testing.assert_allclose(np.asarray(out), 1.0)

    # Async + fusion: several small allreduces in flight together.
    hs = [hvd.allreduce_async(jnp.array([float(rank + i)]), average=False,
                              name=f"fused.{i}") for i in range(4)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   2.0 * i + 1.0)

    # Sparse allreduce (IndexedSlices -> allgather of values+indices,
    # the reference's tensorflow/__init__.py:67-78 path) across REAL
    # processes: rank r contributes row r with value r+1.
    from horovod_tpu import IndexedSlices
    from horovod_tpu.ops.sparse import as_dense

    sl = IndexedSlices(jnp.full((1, 2), float(rank + 1), jnp.float32),
                       jnp.array([rank], jnp.int32), (2, 2))
    out = hvd.allreduce(sl, average=False, name="sparse.op")
    np.testing.assert_allclose(np.asarray(as_dense(out)),
                               [[1.0, 1.0], [2.0, 2.0]])

    # Reduce operators across REAL processes (post-v0.13 op= API):
    # rank r contributes r+1, so min/max/product are all distinct; the
    # adasum of [1,0] and [0,2] (orthogonal) is their sum; mismatched
    # ops for one name must fail validation on both ranks.
    import jax.numpy as _jnp

    x = _jnp.array([float(rank + 1)])
    assert float(hvd.allreduce(x, op=hvd.Min, name="red.min")[0]) == 1.0
    assert float(hvd.allreduce(x, op=hvd.Max, name="red.max")[0]) == 2.0
    assert float(hvd.allreduce(x, op=hvd.Product,
                               name="red.prod")[0]) == 2.0
    ada = hvd.allreduce(_jnp.array([1.0, 0.0]) if rank == 0
                        else _jnp.array([0.0, 2.0]),
                        op=hvd.Adasum, name="red.adasum")
    np.testing.assert_allclose(np.asarray(ada), [1.0, 2.0], rtol=1e-6)
    from horovod_tpu import HorovodError as _HErr

    try:
        hvd.allreduce(x, op=hvd.Min if rank == 0 else hvd.Max,
                      name="red.bad")
        raise AssertionError("mismatched reduce ops did not raise")
    except _HErr as e:
        assert "Mismatched reduce operations" in str(e), str(e)

    # Reducescatter across REAL processes (post-v0.13): each rank gets
    # its own chunk of the reduction — here, half of sum_r(arange+r).
    out = hvd.reducescatter(_jnp.arange(4.0) + rank, average=False,
                            name="red.rscatter")
    want = (2.0 * np.arange(4.0) + 1.0)[2 * rank:2 * rank + 2]
    np.testing.assert_allclose(np.asarray(out), want)
    out = hvd.reducescatter(_jnp.arange(4.0) + rank, average=True,
                            name="red.rscatter.avg")
    np.testing.assert_allclose(np.asarray(out), want / 2.0)

    # Alltoall across REAL processes (post-v0.13), ragged splits: rank 0
    # sends [1 row to 0, 2 rows to 1]; rank 1 sends [2, 1].  Receiver r
    # concatenates in sender order.
    mine = _jnp.asarray(np.arange(3.0).reshape(3, 1) + 100 * rank)
    out = np.asarray(hvd.alltoall(mine,
                                  splits=[1, 2] if rank == 0 else [2, 1],
                                  name="red.a2a"))
    if rank == 0:
        np.testing.assert_allclose(out[:, 0], [0, 100, 101])
    else:
        np.testing.assert_allclose(out[:, 0], [1, 2, 102])
    hvd.barrier()

    # Object collectives across REAL processes: per-rank pickles of
    # genuinely different sizes ride the ragged allgather; broadcast
    # ships the root's object to the non-root.
    from horovod_tpu import allgather_object, broadcast_object

    objs = allgather_object({"rank": rank, "pad": "x" * (10 * rank)})
    assert [o["rank"] for o in objs] == [0, 1], objs
    assert len(objs[1]["pad"]) == 10
    got = broadcast_object({"resume": 7} if rank == 0 else None,
                           root_rank=0)
    assert got == {"resume": 7}, got
    print(f"BASIC_OK rank={rank}")


def scenario_mismatch(hvd):
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    # Real cross-rank disagreement: different shapes for the same name.
    x = jnp.zeros((2 + rank,), jnp.float32)
    try:
        hvd.allreduce(x, name="bad.shape")
    except HorovodError as e:
        assert "Mismatched allreduce tensor shapes" in str(e), str(e)
        print(f"MISMATCH_OK rank={rank}")
        return
    raise AssertionError("mismatched allreduce did not raise")


def scenario_stall(hvd):
    import jax.numpy as jnp

    rank = hvd.rank()
    threshold = float(os.environ["HOROVOD_STALL_WARNING_SECONDS"])
    if rank == 0:
        h = hvd.allreduce_async(jnp.ones((2,)), name="late.op",
                                average=False)
        # Worker 1 sits out past the stall threshold; the coordinator's
        # background tick must print a warning naming it.
        out = hvd.synchronize(h)  # completes once rank 1 finally submits
        np.testing.assert_allclose(np.asarray(out), 2.0)
    else:
        time.sleep(3.0 * threshold)
        out = hvd.allreduce(jnp.ones((2,)), name="late.op", average=False)
        np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"STALL_OK rank={rank}")


def scenario_shutdown(hvd):
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    if rank == 0:
        # This op can never complete: rank 1 shuts down instead of
        # submitting.  The SHUTDOWN it triggers must poison the handle.
        h = hvd.allreduce_async(jnp.ones((2,)), name="doomed.op",
                                average=False)
        try:
            hvd.synchronize(h)
        except HorovodError as e:
            assert "shut down" in str(e), str(e)
            print(f"SHUTDOWN_OK rank={rank}")
            return
        raise AssertionError("shutdown did not poison the pending op")
    else:
        time.sleep(1.0)
        hvd.shutdown()
        print(f"SHUTDOWN_OK rank={rank}")


def scenario_dead_worker(hvd):
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    # Barrier first so every rank is fully initialized and connected
    # before the victim dies — otherwise, under machine load, the death
    # can land mid-startup on a slow survivor and surface as a different
    # error than the pending-op diagnosis this test is about.
    hvd.allreduce(jnp.ones((1,)), name="pre.death.barrier", average=False)
    # The last rank dies; EVERY survivor (controller and plain workers
    # alike) must get a diagnosed failure and exit promptly.
    if rank < hvd.size() - 1:
        h = hvd.allreduce_async(jnp.ones((2,)), name="orphaned.op",
                                average=False)
        try:
            hvd.synchronize(h)
        except HorovodError as e:
            assert "terminated unexpectedly" in str(e), str(e)
            print(f"DEADWORKER_OK rank={rank}")
            return
        raise AssertionError("dead worker was not detected")
    else:
        time.sleep(1.0)
        os._exit(0)  # die without any shutdown handshake


def scenario_torch_frontend(hvd):
    """The Torch frontend across REAL processes: eager tensor
    collectives and DistributedOptimizer gradient averaging ride the
    TCP control plane (the reference's torch CI leg under mpirun)."""
    import torch
    import torch.nn as nn

    import horovod_tpu.frontends.torch as thvd

    rank, size = hvd.rank(), hvd.size()
    out = thvd.allreduce(torch.full((3,), float(rank + 1)), average=True,
                         name="t.avg")
    np.testing.assert_allclose(out.numpy(), 1.5)

    model = nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.fill_(float(rank))  # divergent start
    thvd.broadcast_parameters(model.state_dict(), root_rank=0)
    np.testing.assert_allclose(model.weight.detach().numpy(), 0.0)

    opt = torch.optim.SGD(model.parameters(), lr=1.0)
    opt = thvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    # Rank-dependent inputs so per-rank gradients genuinely differ and
    # the averaged update is checkable by hand on every rank.
    x = torch.full((4, 2), float(rank + 1))
    y = torch.ones((4, 1))
    opt.zero_grad()
    loss = ((model(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    # With w=0: grad_r = 2*mean_i(x_i*(0-1)) = -2*(r+1) per component;
    # averaged over ranks r=0..size-1: -2*mean(r+1) = -(size+1).
    want = (2.0 * np.mean([r + 1 for r in range(size)])) * 1.0
    np.testing.assert_allclose(model.weight.detach().numpy(), want,
                               rtol=1e-5)

    # broadcast_optimizer_state across REAL processes: non-root starts
    # with a divergent lr AND no momentum buffers; root's full
    # state_dict (momentum included) must land.
    m2 = nn.Linear(2, 1, bias=False)
    o2 = torch.optim.SGD(m2.parameters(), lr=0.5, momentum=0.9)
    if rank == 0:
        ((m2(torch.ones(1, 2))).sum()).backward()
        o2.step()  # creates the momentum buffer on root only
    else:
        o2.param_groups[0]["lr"] = 99.0
    thvd.broadcast_optimizer_state(o2, root_rank=0)
    assert o2.param_groups[0]["lr"] == 0.5, o2.param_groups[0]["lr"]
    assert any("momentum_buffer" in st
               for st in o2.state_dict()["state"].values())

    # SyncBatchNorm across REAL processes: each rank normalizes ITS half
    # of a batch with statistics spanning BOTH halves — output, input
    # gradients, and running stats must match stock BatchNorm1d applied
    # to the full batch (the defining property; per-rank BN would use
    # divergent means).
    g = torch.Generator().manual_seed(7)
    full = torch.randn(8, 3, generator=g) * 2.0 + 1.0
    gout = torch.randn(8, 3, generator=g)
    half = full[rank * 4:(rank + 1) * 4].clone().requires_grad_(True)
    sbn = thvd.SyncBatchNorm(3, momentum=0.4)
    out = sbn(half)
    out.backward(gout[rank * 4:(rank + 1) * 4])

    ref_in = full.clone().requires_grad_(True)
    ref = torch.nn.BatchNorm1d(3, momentum=0.4)
    ref_out = ref(ref_in)
    ref_out.backward(gout)
    np.testing.assert_allclose(
        out.detach().numpy(),
        ref_out.detach().numpy()[rank * 4:(rank + 1) * 4], atol=1e-5)
    np.testing.assert_allclose(
        half.grad.numpy(),
        ref_in.grad.numpy()[rank * 4:(rank + 1) * 4], atol=1e-5)
    np.testing.assert_allclose(sbn.running_mean.numpy(),
                               ref.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(sbn.running_var.numpy(),
                               ref.running_var.numpy(), atol=1e-4)
    print(f"TORCH_OK rank={rank}")


def scenario_spmd_train(hvd):
    """The static fast path across REAL processes: one jitted SPMD train
    step over the global (2-process) mesh.  Verifies (a) training works
    and losses agree bit-for-bit on every rank, and (b) the
    ``shard_local_batch`` input model — each process contributing only
    its own rows — produces the same global batch as every host holding
    the full array (``shard_batch``)."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.parallel.training import (make_train_step,
                                               shard_batch,
                                               shard_local_batch)

    rank, size = hvd.rank(), hvd.size()
    w_true = jnp.array([2.0, -3.0])
    X = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (64, 2)))
    y = np.asarray(X @ np.asarray(w_true))

    params = {"w": jnp.zeros((2,))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = optax.sgd(0.1)

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    step = make_train_step(loss_fn, opt)
    # Per-process input pipeline: this rank loads ONLY its rows.
    n_local = len(X) // size
    lo = rank * n_local
    batch = shard_local_batch((X[lo:lo + n_local], y[lo:lo + n_local]))
    opt_state = opt.init(params)
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
    final = float(loss)
    assert final < 1e-3, final
    # Bit-for-bit agreement across ranks: summing identical f32 values
    # over 2 ranks is exact, so any divergence breaks the equality.
    total = float(np.asarray(hvd.allreduce(jnp.array([final]),
                                           average=False,
                                           name="spmd.final.loss"))[0])
    assert total == size * final, (total, final)

    # Equivalence: the full-global-array path yields the same first-step
    # loss from the same start (both spell the identical global batch).
    p0 = {"w": jnp.zeros((2,))}
    s0 = opt.init(p0)
    _, _, l_local = step(p0, s0, batch)
    p0 = {"w": jnp.zeros((2,))}
    s0 = opt.init(p0)
    _, _, l_global = step(p0, s0, shard_batch((X, y)))
    np.testing.assert_array_equal(np.asarray(l_local), np.asarray(l_global))
    print(f"SPMD_OK rank={rank} loss={final:.6f}")


def scenario_overlap(hvd):
    """Multi-process bucketed streaming (ISSUE 12 tentpole a): the
    overlapped np=2 train step — per-bucket partial cycles negotiated
    over the REAL TCP control plane, mp megakernel reductions,
    take_async feeding in-flight results into the apply — is
    BITWISE-identical to the monolithic mp step, for both the plain
    (single-backward) and the ChainedLoss (segmented) schedule; on the
    steady state every bucket replays from the response cache with
    ZERO new negotiation misses."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu.telemetry as _tel
    from horovod_tpu.core import state as _st
    from horovod_tpu.parallel.overlap import ChainedLoss
    from horovod_tpu.parallel.training import (make_train_step,
                                               shard_local_batch)

    rank, size = hvd.rank(), hvd.size()
    D = 16

    def stage0(p, carry, b):
        x, _y = b
        return jnp.tanh(x @ p["w"] + p["b"])

    def stage1(p, carry, b):
        _x, y = b
        pred = carry @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2)

    chain = ChainedLoss([stage0, stage1])

    def plain_loss(p, b):
        return chain(p, b)

    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    params0 = [{"w": jax.random.normal(k, (D, D)) * D ** -0.5,
                "b": jnp.zeros((D,))} for k in ks]
    X = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (8 * size, D)),
                   dtype="float32")
    Y = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (8 * size, D)),
                   dtype="float32")
    lo = rank * (len(X) // size)
    batch = shard_local_batch((X[lo:lo + len(X) // size],
                               Y[lo:lo + len(Y) // size]))
    opt = optax.adam(1e-3)
    threshold = D * D * 4  # w and b bucket apart per stage

    def run(step, steps=4):
        p, s = params0, opt.init(params0)
        loss = None
        for _ in range(steps):
            p, s, loss = step(p, s, batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(p))
        return p, float(loss)

    def leaves_equal(a, b):
        return all(
            np.asarray(u).tobytes() == np.asarray(v).tobytes()
            for u, v in zip(jax.tree_util.tree_leaves(a),
                            jax.tree_util.tree_leaves(b)))

    fallbacks0 = _tel.metrics().get(
        "overlap.fallbacks", {}).get("value", 0)

    # Leg 1 — segmented schedule (ChainedLoss): streamed mp partial
    # cycles ≡ the monolithic mp step, bitwise after 4 adam steps.
    step_on = make_train_step(chain, opt, donate=False,
                              fusion_threshold=threshold, overlap="on")
    p_on, l_on = run(step_on)
    assert step_on.overlap_active, "mp build fell back"
    assert step_on.segment_count == 2
    assert step_on.bucket_count == 4
    step_off = make_train_step(chain, opt, donate=False,
                               fusion_threshold=threshold, overlap="off")
    p_off, l_off = run(step_off)
    assert l_on == l_off, (l_on, l_off)
    assert leaves_equal(p_on, p_off), "overlapped mp != monolithic mp"
    print(f"OVERLAP_SEG_OK rank={rank} loss={l_on:.6f}")

    # Leg 2 — plain loss (single-backward streaming): same contract.
    step_u_on = make_train_step(plain_loss, opt, donate=False,
                                fusion_threshold=threshold, overlap="on")
    p_u_on, _ = run(step_u_on, 2)
    assert step_u_on.overlap_active
    step_u_off = make_train_step(plain_loss, opt, donate=False,
                                 fusion_threshold=threshold,
                                 overlap="off")
    p_u_off, _ = run(step_u_off, 2)
    assert leaves_equal(p_u_on, p_u_off)
    print(f"OVERLAP_PLAIN_OK rank={rank}")

    # Leg 3 — steady state: every bucket's partial cycle replays from
    # the response cache; two further steps add ZERO negotiation
    # misses on either rank, and the mp bucket counter advances.
    st = _st.global_state()
    cache = st.response_cache
    assert cache is not None
    misses0 = cache.stats.misses
    mp0 = _tel.metrics().get(
        "overlap.mp_buckets_dispatched", {}).get("value", 0)
    p, s = p_on, opt.init(p_on)
    for _ in range(2):
        p, s, _loss = step_on(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    assert cache.stats.misses == misses0, (
        f"steady-state mp buckets renegotiated: "
        f"{cache.stats.misses - misses0} new misses")
    mp_buckets = _tel.metrics()[
        "overlap.mp_buckets_dispatched"]["value"] - mp0
    assert mp_buckets == 2 * step_on.bucket_count, mp_buckets
    fallbacks = _tel.metrics().get(
        "overlap.fallbacks", {}).get("value", 0) - fallbacks0
    assert fallbacks == 0, f"{fallbacks} unexpected overlap fallbacks"

    # Leg 4 — transport fault MID-PARTIAL-CYCLE: rank 1's control-plane
    # socket is hard-reset right before a training step, so the very
    # next bucket's coalesced request frame hits the dead socket
    # mid-flush; the session-resume protocol replays the lost frames
    # (cache replicas stay index-aligned) and the trained parameters
    # stay BITWISE-identical to the uninterrupted monolithic run — the
    # no-new-hang-class contract for partial cycles.
    p, s = params0, opt.init(params0)
    for stepi in range(6):
        if stepi == 3 and rank == 1:
            from horovod_tpu.ops import transport as _tp

            _tp._hard_close(st.transport._sock)
        p, s, _loss = step_on(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    q, t = params0, opt.init(params0)
    for _ in range(6):
        q, t, _loss = step_off(q, t, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(q))
    assert leaves_equal(p, q), \
        "post-reconnect overlapped params != uninterrupted monolithic"
    if rank == 1:
        got = _tel.metrics().get("transport.reconnects",
                                 {}).get("value", 0)
        assert got >= 1, f"no reconnect was recorded: {got}"
    print(f"OVERLAP_OK rank={rank} buckets={mp_buckets}")


def scenario_chaos(hvd):
    """hvd-chaos acceptance (ISSUE 9): a worker's control-plane
    connection dies mid-training; the worker reconnects with backoff,
    the session-resume protocol replays the lost frames (re-syncing its
    response-cache replica), and training completes BITWISE-identical
    to the uninterrupted run — replayed in numpy below with the exact
    same f32 arithmetic."""
    import jax.numpy as jnp

    rank, size = hvd.rank(), 2
    assert hvd.size() == size
    w_true = np.array([1.5, -2.0], dtype="float32")
    rng = np.random.RandomState(5 + rank)
    X = rng.normal(size=(16, 2)).astype("float32")
    y = X @ w_true
    w = np.zeros(2, dtype="float32")
    steps = 20
    for step in range(steps):
        if step == 10 and rank == 1:
            # Transient network fault: hard-reset THIS rank's
            # control-plane socket mid-run (the chaos transport.reset
            # wire effect, applied directly so the firing point is
            # exact).  The reconnect path must absorb it.
            from horovod_tpu.core import state as _st
            from horovod_tpu.ops import transport as _tp

            _tp._hard_close(_st.global_state().transport._sock)
        g = (2.0 * X.T @ (X @ w - y) / len(X)).astype("float32")
        g_avg = np.asarray(hvd.allreduce(
            jnp.asarray(g), average=True, name=f"chaos.g.{step}"))
        w = (w - 0.1 * g_avg).astype("float32")

    # The uninterrupted run, replayed in f32 numpy.
    datas = []
    for r in range(size):
        rr = np.random.RandomState(5 + r)
        Xr = rr.normal(size=(16, 2)).astype("float32")
        datas.append((Xr, Xr @ w_true))
    we = np.zeros(2, dtype="float32")
    for _ in range(steps):
        gs = [(2.0 * Xr.T @ (Xr @ we - yr) / len(Xr)).astype("float32")
              for Xr, yr in datas]
        we = (we - 0.1 * ((gs[0] + gs[1]) / 2.0)).astype("float32")
    np.testing.assert_array_equal(w, we)

    if rank == 1:
        import horovod_tpu.telemetry as _tel

        snap = _tel.metrics()
        got = snap.get("transport.reconnects", {}).get("value", 0)
        assert got >= 1, f"no reconnect was recorded: {got}"
    print(f"CHAOS_MP_OK rank={rank} w=[{w[0]:.6f},{w[1]:.6f}]")


def scenario_dead_controller(hvd):
    """Rank 0 (the controller) dies without any handshake.  Rank 0 also
    hosts the jax coordination service, so jax's client usually
    fatal-kills the worker the instant the service socket closes; when
    our transport's EOF detection wins that race instead, the pending op
    fails with the controller-death diagnosis.  Either way the worker
    must terminate promptly — the launch-level assertion."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    if rank == 0:
        time.sleep(1.0)
        os._exit(0)  # controller dies without any shutdown handshake
    else:
        h = hvd.allreduce_async(jnp.ones((2,)), name="orphaned.op",
                                average=False)
        try:
            hvd.synchronize(h)
        except HorovodError as e:
            assert "controller terminated unexpectedly" in str(e), str(e)
            print(f"DEADCTRL_OK rank={rank}")
            return
        raise AssertionError("dead controller was not detected")


def scenario_clean_exit(hvd):
    """Rank 1 finishes WITHOUT calling hvd.shutdown(): the transport's
    atexit handshake must turn the interpreter exit into a cooperative
    shutdown — rank 0 gets the plain shut-down error (no crash
    diagnosis), and both processes still exit rc=0 through
    jax.distributed's exit barrier."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    out = hvd.allreduce(jnp.ones((2,)), name="warm.op", average=False)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    if rank == 1:
        main.skip_shutdown = True
        print("CLEANEXIT_OK rank=1")
        return  # interpreter exit fires the handshake
    try:
        hvd.allreduce(jnp.ones((2,)), name="late.op", average=False)
        raise AssertionError("expected the shut-down error")
    except HorovodError as e:
        assert "terminated unexpectedly" not in str(e), str(e)
        print("CLEANEXIT_OK rank=0")


def scenario_tf_function(hvd):
    """Compiled-graph collectives across REAL processes (round 4): a
    tf.function-compiled step allreduces mid-graph through the
    py_function bridge — the TF2 spelling of the reference's
    session.run(train_op) with AsyncOpKernels enqueueing from graph
    execution (mpi_ops.cc:270-298)."""
    import tensorflow as tf

    import horovod_tpu.frontends.tensorflow as hvdtf

    rank = hvd.rank()

    @tf.function
    def f(x):
        return hvdtf.allreduce(x, average=False, name="tffn.op")

    for i in range(3):  # repeated executions reuse the trace-time name
        out = f(tf.constant([float(rank + 1 + i)]))
        np.testing.assert_allclose(out.numpy(), [3.0 + 2.0 * i])

    w = tf.Variable([0.0])

    @tf.function
    def train_step():
        with hvdtf.DistributedGradientTape(tf.GradientTape()) as tape:
            # Rank-dependent loss: grad_r = 2*(w - (r+1)); averaged over
            # the 2 ranks: 2*(w - 1.5) — the compiled update must use
            # the REDUCED gradient identically on both ranks.
            loss = (w[0] - float(rank + 1)) ** 2
        (g,) = tape.gradient(loss, [w])
        w.assign_sub(0.25 * g)
        return loss

    for _ in range(25):
        train_step()
    np.testing.assert_allclose(w.numpy(), [1.5], atol=1e-3)
    print(f"TFFN_OK rank={rank}")


def _sync_expect_abandoned(hvd, h, who, t0: float, budget: float = 20.0):
    """synchronize(h) with a short timeout, expecting the coordinator's
    group-wide abandonment ERROR (not the local-fallback timeout text).
    ``who`` pins the named withdrawing rank, or None when several ranks
    race and the winner is nondeterministic.  The short timeout applies
    ONLY to this call — the env is read per call, so recovery
    collectives and co-launched scenarios keep the default."""
    from horovod_tpu import HorovodError

    prev = os.environ.get("HOROVOD_TPU_SYNC_TIMEOUT")
    os.environ["HOROVOD_TPU_SYNC_TIMEOUT"] = "2"
    try:
        hvd.synchronize(h)
        raise AssertionError("expected the withdrawal error")
    except HorovodError as e:
        want = ("was abandoned: rank" if who is None
                else f"was abandoned: rank {who}")
        assert want in str(e), str(e)
    finally:
        if prev is None:
            os.environ.pop("HOROVOD_TPU_SYNC_TIMEOUT", None)
        else:
            os.environ["HOROVOD_TPU_SYNC_TIMEOUT"] = prev
    assert time.monotonic() - t0 < budget, "fail-fast regressed"


def scenario_withdraw(hvd):
    """A rank whose synchronize times out WITHDRAWS the op group-wide:
    the coordinator broadcasts an ERROR response and the op fails on
    every rank within the grace window — instead of the round-3 behavior
    (local-only withdrawal; peers later execute a response the withdrawer
    skips, or serially eat their own 300 s timeouts).  The failure is
    surgical: the group survives and later collectives work."""
    import jax.numpy as jnp

    rank = hvd.rank()

    # Leg 1 — a WORKER (rank 1) gives up: the WITHDRAW frame rides the
    # TCP control plane to the coordinator.
    t0 = time.monotonic()
    if rank == 1:
        h = hvd.allreduce_async(jnp.ones((2,)), name="abandoned.w",
                                average=False)
        _sync_expect_abandoned(hvd, h, 1, t0)
    else:
        time.sleep(4.0)  # outlive the peer's timeout; never submit
    out = hvd.allreduce(jnp.ones((2,)), name="recover.w", average=False)
    np.testing.assert_allclose(np.asarray(out), 2.0)

    # Leg 2 — the CONTROLLER (rank 0) gives up: withdrawal goes straight
    # into the in-process coordinator, ERROR still broadcasts to all.
    t1 = time.monotonic()
    if rank == 0:
        h = hvd.allreduce_async(jnp.ones((2,)), name="abandoned.c",
                                average=False)
        _sync_expect_abandoned(hvd, h, 0, t1)
    else:
        time.sleep(4.0)
    out = hvd.allreduce(jnp.ones((2,)), name="recover.c", average=False)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"WITHDRAW_OK rank={rank}")


def scenario_checkpoint(hvd):
    import jax.numpy as jnp

    from horovod_tpu.utils.checkpoint import (restore_checkpoint,
                                              resume_epoch,
                                              save_checkpoint)

    rank = hvd.rank()
    path = os.environ["HVD_TPU_TEST_CKPT"]
    good = {"w": np.full((3,), 7.0, "float32")}
    if rank == 0:
        assert save_checkpoint(path, good, step=5)
    else:
        # Non-root never writes (reference rank-0 convention).
        assert not save_checkpoint(path, {"w": np.zeros((3,))}, step=5)
    while not os.path.exists(path):
        time.sleep(0.05)
    # Each rank starts from divergent state; restore must converge all
    # ranks to root's values via the broadcast.
    mine = {"w": jnp.full((3,), float(rank + 1))}
    restored = restore_checkpoint(path, mine)
    np.testing.assert_allclose(np.asarray(restored["w"]), 7.0)
    assert resume_epoch(path) == 5
    print(f"CKPT_OK rank={rank}")


def scenario_join(hvd):
    """hvd.join() across REAL processes (post-v0.13 API; the v0.13
    reference could only hang on uneven workloads): rank 0 runs out of
    data after 2 steps, rank 1 trains 4; the joined rank contributes
    zeros until everyone joins; both learn the last joining rank.  The
    barrier is reusable, and a broadcast whose root has joined fails
    with a clean diagnosis instead of hanging."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()
    steps = 2 if rank == 0 else 4
    for i in range(steps):
        out = hvd.allreduce(jnp.full((3,), float(rank + 1)),
                            average=False, name=f"join.step.{i}")
        want = 3.0 if i < 2 else 2.0  # rank 0 joined: zeros + rank 1's 2
        np.testing.assert_allclose(np.asarray(out), want)
        if i >= 2:
            # Ragged allgather with a joined rank: 0 rows from rank 0.
            g = hvd.allgather(jnp.full((2, 2), 7.0),
                              name=f"join.gather.{i}")
            assert np.asarray(g).shape == (2, 2), g.shape
            np.testing.assert_allclose(np.asarray(g), 7.0)
    assert hvd.join() == 1  # rank 1 joins last (it had more batches)

    # The barrier is reusable; a joined root is a clean error.
    if rank == 0:
        assert hvd.join() == 1
    else:
        try:
            hvd.broadcast(jnp.ones((2,)), root_rank=0, name="joined.root")
            raise AssertionError("expected the joined-root error")
        except HorovodError as e:
            assert "has joined" in str(e), str(e)
        assert hvd.join() == 1
    out = hvd.allreduce(jnp.ones((2,)), name="post.join", average=False)
    np.testing.assert_allclose(np.asarray(out), 2.0)

    # Round 3 of the barrier: an async op outstanding ACROSS join().  It
    # can FUSE with a tensor completed by this rank's join, so the
    # joined rank must execute the mixed buffer — its real value in its
    # own slot, zeros in the peer-only slot — identically to the peers'
    # fused flat buffer (round-4 review finding).  (Fusion of the two
    # tensors depends on them becoming ready within one 5 ms tick —
    # overwhelmingly likely with back-to-back submits; if they miss, the
    # assertions still hold via unfused responses.)
    if rank == 0:
        h = hvd.allreduce_async(jnp.full((4,), 1.0), name="fuse.mine",
                                average=False)
        assert hvd.join() == 1
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)), 3.0)
    else:
        time.sleep(0.5)  # rank 0's submit + JOIN land first
        ha = hvd.allreduce_async(jnp.full((4,), 2.0), name="fuse.mine",
                                 average=False)
        hb = hvd.allreduce_async(jnp.full((2,), 5.0), name="fuse.peer",
                                 average=False)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(ha)), 3.0)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(hb)), 5.0)
        assert hvd.join() == 1
    print(f"JOIN_OK rank={rank}")


def scenario_process_sets(hvd):
    """Process sets across REAL processes (post-v0.13 API; the v0.13
    reference fixes everything to MPI_COMM_WORLD): np=3, set {0,2}
    negotiates and executes over its own sub-mesh while rank 1 runs a
    disjoint singleton set, then everyone meets again in a global op.
    Registration is collective and validated; a non-member submit
    raises."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank, size = hvd.rank(), hvd.size()
    assert size == 3, size
    ps = hvd.add_process_set([0, 2])
    assert ps.included() == (rank in (0, 2))
    if ps.included():
        out = hvd.allreduce(jnp.full((2,), float(rank + 1)),
                            average=False, process_set=ps, name="ps.sum")
        np.testing.assert_allclose(np.asarray(out), 4.0)  # ranks 0+2: 1+3
        out = hvd.allreduce(jnp.full((2,), float(rank + 1)),
                            average=True, process_set=ps, name="ps.avg")
        np.testing.assert_allclose(np.asarray(out), 2.0)
        # Ragged allgather inside the set: member m contributes m+1 rows.
        mine = jnp.full((ps.rank() + 1, 2), float(rank))
        g = np.asarray(hvd.allgather(mine, process_set=ps,
                                     name="ps.gather"))
        assert g.shape == (3, 2), g.shape
        np.testing.assert_allclose(g[:1], 0.0)
        np.testing.assert_allclose(g[1:], 2.0)
        # Broadcast rooted at GLOBAL rank 2 (set-local 1).
        out = hvd.broadcast(jnp.full((2,), float(rank)), 2,
                            process_set=ps, name="ps.bcast")
        np.testing.assert_allclose(np.asarray(out), 2.0)
    else:
        try:
            hvd.allreduce(jnp.ones((2,)), process_set=ps, name="ps.bad")
            raise AssertionError("non-member submit did not raise")
        except HorovodError as e:
            assert "not a member" in str(e), str(e)
    # A second, disjoint set keeps its own coordinator and sub-mesh.
    ps1 = hvd.add_process_set([1])
    if rank == 1:
        out = hvd.allreduce(jnp.array([5.0]), average=False,
                            process_set=ps1, name="ps1.solo")
        np.testing.assert_allclose(np.asarray(out), 5.0)
    # AUTO-NAMED ops: set members consumed set-namespaced names, so an
    # unnamed GLOBAL op right after must still agree across ALL ranks
    # (review finding: a shared counter would desync members from
    # non-members and stall/misroute here).
    if ps.included():
        out = hvd.allreduce(jnp.ones((2,)), average=False, process_set=ps)
        np.testing.assert_allclose(np.asarray(out), 2.0)
    out = hvd.allreduce(jnp.full((2,), 2.0), average=False)  # unnamed
    np.testing.assert_allclose(np.asarray(out), 2.0 * size)
    # Chaining a set output into a global collective re-places it.
    if ps.included():
        chained = hvd.allreduce(jnp.ones((2,)), average=False,
                                process_set=ps, name="ps.chain")
    else:
        chained = jnp.full((2,), 2.0)
    out = hvd.allreduce(chained, average=False, name="ps.chain.world")
    np.testing.assert_allclose(np.asarray(out), 6.0)
    # And the global set still works for everyone afterwards.
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="ps.world")
    np.testing.assert_allclose(np.asarray(out), float(size))
    print(f"PSETS_OK rank={rank}")


def scenario_elastic(hvd):
    """Elastic relaunch across REAL processes: rank 1 dies hard at step
    5 of the first incarnation; rank 0 diagnoses the dead peer, exits
    EX_TEMPFAIL, and the --elastic launcher relaunches the job.  The
    second incarnation resumes from the last commit (step 4) and must
    converge to EXACTLY the weights of an uninterrupted run — the test
    replays the arithmetic in numpy and compares."""
    import jax.numpy as jnp

    from horovod_tpu import elastic

    rank = hvd.rank()
    edir = os.environ["HVD_TPU_ELASTIC_DIR"]
    marker = os.path.join(edir, "victim_died")
    total = 8

    w_true = np.array([1.0, -2.0], dtype="float32")
    rng = np.random.RandomState(17 + rank)
    X = rng.normal(size=(total, 16, 2)).astype("float32")
    y = X @ w_true

    state = elastic.State(w=jnp.zeros((2,)), step=0)

    @elastic.run
    def train(state):
        if state.step > 0:
            print(f"ELASTIC_RESUMED rank={rank} step={state.step}")
        while state.step < total:
            i = state.step
            if rank == 1 and i == 5 and not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # hard failure, no handshake
            xb, yb = jnp.asarray(X[i]), jnp.asarray(y[i])
            grad = 2.0 * xb.T @ (xb @ state.w - yb) / xb.shape[0]
            grad = hvd.allreduce(grad, average=True, name=f"el.grad.{i}")
            state.w = state.w - 0.1 * grad
            state.step += 1
            if state.step % 2 == 0:
                state.commit()
        return np.asarray(state.w)

    w = train(state)
    print(f"ELASTIC_OK rank={rank} w={w.round(6).tolist()}")


def scenario_np8(hvd):
    """np=8 scale-out of the fusion/failure semantics (the richest
    behaviors had only ever run at np<=3): a 24-op fusion storm, two
    OVERLAPPING process sets with concurrent in-flight ops on both
    coordinators, a withdraw RACE (four ranks abandon the same op
    simultaneously), and a stall warning naming the THREE missing ranks
    — the reference ran its whole suite under real ``mpirun -np 2``
    (.travis.yml:96-103); this is that leg at 4x the scale."""
    import jax.numpy as jnp

    rank, size = hvd.rank(), hvd.size()
    assert size == 8, size

    # Leg 1 — fusion storm: 24 async allreduces in flight at once from
    # every rank.  Values are per-op distinct so a fused-buffer
    # misroute (wrong offsets) cannot cancel out.
    hs = [hvd.allreduce_async(jnp.full((8,), float(rank + 1) * (i + 1)),
                              average=False, name=f"storm.{i}")
          for i in range(24)]
    for i, h in enumerate(hs):  # sum_r (r+1)(i+1) = 36(i+1)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   36.0 * (i + 1))

    # Leg 2 — OVERLAPPING process sets {0..4} and {3..7}: ranks 3 and 4
    # are members of both and keep ops in flight on both per-set
    # coordinators at once.
    psa = hvd.add_process_set([0, 1, 2, 3, 4])
    psb = hvd.add_process_set([3, 4, 5, 6, 7])
    ha = hb = None
    if psa.included():
        ha = hvd.allreduce_async(jnp.full((2,), float(rank + 1)),
                                 average=False, process_set=psa,
                                 name="ov.a")
    if psb.included():
        hb = hvd.allreduce_async(jnp.full((2,), float(rank + 1)),
                                 average=False, process_set=psb,
                                 name="ov.b")
    if ha is not None:  # ranks 0..4 contribute 1+2+3+4+5
        np.testing.assert_allclose(np.asarray(hvd.synchronize(ha)), 15.0)
    if hb is not None:  # ranks 3..7 contribute 4+5+6+7+8
        np.testing.assert_allclose(np.asarray(hvd.synchronize(hb)), 30.0)
    # The global set still negotiates cleanly across all 8 afterwards.
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="ov.world")
    np.testing.assert_allclose(np.asarray(out), 8.0)

    # Leg 3 — withdraw RACE: ranks 0-3 give up on the SAME never-ready
    # op at the same moment (four concurrent WITHDRAW frames, one of
    # them in-process on the controller); every withdrawer gets the
    # coordinator's group-wide abandonment error, and the group
    # survives.
    t0 = time.monotonic()
    if rank < 4:
        h = hvd.allreduce_async(jnp.ones((2,)), name="raced.op",
                                average=False)
        # who=None: four ranks race to withdraw; the named winner is
        # nondeterministic.
        _sync_expect_abandoned(hvd, h, None, t0, budget=30.0)
    else:
        time.sleep(5.0)  # outlive the racers' timeouts; never submit
    out = hvd.allreduce(jnp.ones((2,)), name="race.recover",
                        average=False)
    np.testing.assert_allclose(np.asarray(out), 8.0)

    # Leg 4 — stall warning naming THREE late ranks: 5, 6 and 7 sit out
    # past the threshold; the controller's stall report must list them
    # all (the np=2 leg only ever named one).
    threshold = float(os.environ["HOROVOD_STALL_WARNING_SECONDS"])
    if rank < 5:
        h = hvd.allreduce_async(jnp.ones((2,)), name="late8.op",
                                average=False)
        out = hvd.synchronize(h)
    else:
        time.sleep(3.0 * threshold)
        out = hvd.allreduce(jnp.ones((2,)), name="late8.op",
                            average=False)
    np.testing.assert_allclose(np.asarray(out), 8.0)
    print(f"NP8_OK rank={rank}")


def scenario_elastic2(hvd):
    """Elastic surviving TWO sequential hard deaths: rank 1 dies at step
    3 (incarnation 1) and again at step 7 (incarnation 2); each relaunch
    resumes from the last commit and the final weights must match an
    uninterrupted run, replayed in numpy in-process (both ranks' data
    streams are deterministic functions of the rank seed, so every rank
    can replay the whole job)."""
    import jax.numpy as jnp

    from horovod_tpu import elastic

    rank = hvd.rank()
    edir = os.environ["HVD_TPU_ELASTIC_DIR"]
    markers = [os.path.join(edir, "victim_died_1"),
               os.path.join(edir, "victim_died_2")]
    deaths = {3: markers[0], 7: markers[1]}
    total = 10

    w_true = np.array([1.0, -2.0], dtype="float32")
    data = []
    for r in range(2):
        rng = np.random.RandomState(23 + r)
        X = rng.normal(size=(total, 16, 2)).astype("float32")
        data.append((X, X @ w_true))
    X, y = data[rank]

    state = elastic.State(w=jnp.zeros((2,)), step=0)

    @elastic.run
    def train(state):
        if state.step > 0:
            print(f"ELASTIC2_RESUMED rank={rank} step={state.step}")
        while state.step < total:
            i = state.step
            marker = deaths.get(i)
            if rank == 1 and marker and not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # hard failure, no handshake
            xb, yb = jnp.asarray(X[i]), jnp.asarray(y[i])
            grad = 2.0 * xb.T @ (xb @ state.w - yb) / xb.shape[0]
            grad = hvd.allreduce(grad, average=True, name=f"el2.grad.{i}")
            state.w = state.w - 0.1 * grad
            state.step += 1
            if state.step % 2 == 0:
                state.commit()
        return np.asarray(state.w)

    w = train(state)
    # In-process replay of the uninterrupted arithmetic (f32 like the
    # training loop).
    want = np.zeros(2, dtype="float32")
    for i in range(total):
        grads = [2.0 * Xr[i].T @ (Xr[i] @ want - yr[i]) / Xr[i].shape[0]
                 for Xr, yr in data]
        want = want - 0.1 * (grads[0] + grads[1]) / 2.0
    np.testing.assert_allclose(w, want, atol=1e-4)
    print(f"ELASTIC2_OK rank={rank}")


def scenario_verify(hvd):
    """verify_program across REAL processes (hvd-analyze pass 1): the
    matching program verifies clean over the TCP control plane, then
    every divergence kind — dtype, shape, order, count, and the
    process-set wait-for CYCLE no runtime check can catch — fails at
    verify time with a diagnostic naming the first divergent entry and
    both ranks' records.  All cases run in ONE launch, and — true to
    "verify BEFORE the data plane" — no collective is ever synchronized:
    the divergent ops are enqueued async only, so every negotiation
    either errors or stays pending (poisoned at shutdown) and the group
    stays healthy between cases; verify_program's reset isolates each
    round."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError, verify_program
    from horovod_tpu.analysis import program as _prog

    rank = hvd.rank()

    # Round 0 — identical signatures verify clean.  The roots diverge,
    # but root_rank is deliberately OUTSIDE the signature (the runtime
    # validator owns it): this also pins the verifier's scope.
    _prog.recorder().clear()
    hvd.broadcast_async(jnp.ones((2,)), root_rank=rank, name="v.same")
    rep = verify_program()
    assert rep.ranks == 2 and rep.entries == 1, rep
    print(f"VERIFY_OK rank={rank}")

    def expect(case: str, want: str, both_records: bool = True):
        try:
            verify_program()
            raise AssertionError(f"case {case}: expected divergence")
        except HorovodError as e:
            assert want in str(e), (case, str(e))
            if both_records:
                assert "rank 0" in str(e) and "rank 1" in str(e), str(e)
        print(f"VERIFY_DIVERGE_OK rank={rank} case={case}")

    # dtype: same name, one rank traced float32, the other int32.
    hvd.allreduce_async(jnp.ones(
        (2,), jnp.float32 if rank == 0 else jnp.int32),
        average=False, name="v.dtype")
    expect("dtype", "Mismatched data types")

    # shape: same name, rank-dependent shape.
    hvd.allreduce_async(jnp.ones((2 + rank,)), average=False,
                        name="v.shape")
    expect("shape", "Mismatched tensor shapes")

    # order: the two ranks enqueue the same two ops swapped — the
    # name-keyed coordinator would stall on this forever.  (The dtype
    # rides the rank so the swapped negotiations error out instead of
    # completing into data-plane work this scenario never wants.)
    dt = jnp.float32 if rank == 0 else jnp.int32
    for n in (["v.a", "v.b"] if rank == 0 else ["v.b", "v.a"]):
        hvd.allreduce_async(jnp.ones((2,), dt), average=False, name=n)
    expect("order", "Mismatched tensor names")

    # count: rank 1 traced one collective more than rank 0 (the common
    # entry is signature-identical — divergent root only — so the
    # count check, not a field diff, is what fires).
    hvd.broadcast_async(jnp.ones((2,)), root_rank=rank, name="v.c0")
    if rank == 1:
        hvd.allreduce_async(jnp.ones((2,)), average=False, name="v.c1")
    expect("count", "Rank-divergent collective count",
           both_records=False)

    # process-set cycle: rank 0 traces set-1-then-set-2, rank 1 the
    # swap.  Each set's coordinator would see a perfectly consistent
    # stream, so only the wait-for-graph check can catch the deadlock
    # synchronous callers would hit.  Recorded through the public
    # capture hook so the cycle stands alone in the signature
    # (registering real sets would prepend its own collective rounds).
    _prog.recorder().clear()
    order = [("v.x", 1), ("v.y", 2)] if rank == 0 \
        else [("v.y", 2), ("v.x", 1)]
    for n, psid in order:
        _prog.record_collective("allreduce", n, "float32", (2,),
                                reduce_op="sum", process_set_id=psid)
    expect("cycle", "Potential process-set deadlock cycle")
    print(f"VERIFY_ALL_OK rank={rank}")


def scenario_cache(hvd):
    """Response-cache steady state + every invalidation hook across REAL
    processes (ops/cache.py): after the first negotiation of a repeated
    named program, workers ship one coalesced bit-vector frame per tick
    and rank 0 replays cached responses (skipping submit/
    construct_response); a mid-run program change, hvd.join(), process-
    set add/remove and an autotune threshold update each flush the
    cache with a logged marker while results stay exactly correct.
    Runs identically with HVD_TPU_RESPONSE_CACHE=0 (minus the stats
    asserts) — the numerical-identity leg of the acceptance criteria."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError
    from horovod_tpu.core import state as _st

    rank = hvd.rank()
    st = _st.global_state()
    cache = st.response_cache
    cache_on = os.environ.get("HVD_TPU_RESPONSE_CACHE", "1") != "0"
    assert (cache is not None) == cache_on, (cache, cache_on)

    # Leg 1 — steady state: the identical named program for 4 steps.
    # Values are rank- and step-dependent so a replayed response feeding
    # the wrong op (or a stale cached result) cannot produce them.
    for step in range(4):
        for i in range(3):
            out = hvd.allreduce(
                jnp.full((4,), float(rank + 1) * (i + 1)),
                average=False, name=f"c.grad.{i}")
            np.testing.assert_allclose(np.asarray(out), 3.0 * (i + 1))
        g = np.asarray(hvd.allgather(
            jnp.full((rank + 1, 2), float(rank)), name="c.gather"))
        assert g.shape == (3, 2), g.shape
        np.testing.assert_allclose(g[:1], 0.0)
        np.testing.assert_allclose(g[1:], 1.0)
        b = np.asarray(hvd.broadcast(jnp.full((2,), float(rank)), 1,
                                     name="c.bcast"))
        np.testing.assert_allclose(b, 1.0)
    hits = 0
    if cache_on:
        s = cache.stats
        hits = s.hits
        assert s.hits > 0, s  # every rank's replica must be serving
        if rank == 0:
            assert s.replayed_tensors > 0, s
    print(f"CACHE_STEADY_OK rank={rank} hits={hits}")

    # Leg 2 — program change mid-run: the same name returns with a new
    # (rank-divergent) shape.  The cached cycle must flush (logged) and
    # the standard cross-rank mismatch diagnosis must fire — not a
    # stale replay of the old shape.
    try:
        hvd.allreduce(jnp.ones((2 + rank,)), average=False,
                      name="c.grad.0")
        raise AssertionError("changed program did not raise")
    except HorovodError as e:
        assert "Mismatched allreduce tensor shapes" in str(e), str(e)
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="c.recover")
    np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"CACHE_CHANGE_OK rank={rank}")

    # Leg 3 — hvd.join(): rank 0 runs out after 2 steps; negotiations
    # completed via the join must not poison the cache (insertion is
    # disarmed until the release), and results stay exact.
    steps = 2 if rank == 0 else 4
    for i in range(steps):
        out = hvd.allreduce(jnp.full((3,), float(rank + 1)),
                            average=False, name=f"c.join.{i}")
        want = 3.0 if i < 2 else 2.0  # rank 0 joined: zeros + rank 1
        np.testing.assert_allclose(np.asarray(out), want)
    assert hvd.join() == 1
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="c.post.join")
    np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"CACHE_JOIN_OK rank={rank}")

    # Leg 4 — process-set add/remove: both flush every replica at the
    # registration allgather's stream position; set collectives and the
    # global set keep working before, between and after.
    ps = hvd.add_process_set([0, 1])
    out = hvd.allreduce(jnp.full((2,), float(rank + 1)), average=False,
                        process_set=ps, name="c.ps")
    np.testing.assert_allclose(np.asarray(out), 3.0)
    assert hvd.remove_process_set(ps)
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="c.ps.after")
    np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"CACHE_PSETS_OK rank={rank}")

    # Leg 5 — autotune fusion-threshold update: entries survive, the
    # memoized packing plans flush (logged on the coordinator).
    for _ in range(2):  # second pass replays → builds a cached plan
        out = hvd.allreduce(jnp.ones((2,)), average=False, name="c.tune")
        np.testing.assert_allclose(np.asarray(out), 2.0)
    if rank == 0 and st.coordinator is not None:
        st.coordinator.set_fusion_threshold(1 << 20)
    out = hvd.allreduce(jnp.ones((2,)), average=False, name="c.tune")
    np.testing.assert_allclose(np.asarray(out), 2.0)
    print(f"CACHE_TUNE_OK rank={rank}")

    if cache_on:
        s = cache.stats
        assert s.flushes > 0, s
        print(f"CACHE_OK rank={rank} hits={s.hits} flushes={s.flushes}")
    else:
        print(f"CACHE_OK rank={rank} hits=0 flushes=0")


def scenario_metrics(hvd):
    """hvd-telemetry cluster aggregation over the REAL control plane:
    both ranks seed negotiation traffic, then rank 0 pulls every
    rank's snapshot over FRAME_METRICS and asserts the fleet aggregate
    covers all ranks (rank 1 answers from its receive thread while
    blocked in its own barrier).

    The seeding uses deliberately MISMATCHED shapes: the full control
    plane runs — per-rank submits, coalesced frames, rank-0
    validation, ERROR broadcast — with zero data-plane execution, so
    this leg (unlike the np>1 XLA-collective legs) also verifies under
    jax builds whose CPU backend cannot run multiprocess
    computations."""
    import jax.numpy as jnp

    from horovod_tpu import HorovodError

    rank = hvd.rank()

    def control_plane_round(name):
        try:
            hvd.allreduce(jnp.zeros((2 + rank,), jnp.float32), name=name,
                          average=False)
            raise AssertionError(f"mismatched {name} did not raise")
        except HorovodError as e:
            assert "Mismatched allreduce tensor shapes" in str(e), str(e)

    for i in range(3):
        control_plane_round(f"met.{i}")

    local = hvd.metrics()
    assert local["collective.submitted"]["value"] >= 3, local
    assert local["collective.errors"]["value"] >= 3, local
    assert local["collective.negotiate_seconds"]["count"] >= 3, local
    assert local["transport.frames_sent"]["value"] >= 1, local

    if rank == 0:
        agg = hvd.cluster_metrics(timeout=30.0)
        m = agg["collective.submitted"]
        assert m["ranks"] == hvd.size(), m
        assert m["min"] >= 3, m
        assert agg["collective.errors"]["sum"] >= 3 * hvd.size(), agg
        h = agg["collective.negotiate_seconds"]
        assert h["count"] >= 3 * hvd.size(), h
        assert h["p50"] is not None and h["p99"] is not None, h
        assert agg["transport.frames_sent"]["sum"] >= 2, agg
    else:
        try:
            hvd.cluster_metrics(timeout=1.0)
            raise AssertionError("cluster_metrics must be rank-0-only")
        except RuntimeError as e:
            assert "rank-0" in str(e), str(e)
    # Barrier keeps rank 1 alive (and answering pulls) until rank 0's
    # aggregation finished — the mismatch completes negotiation on both
    # ranks, so it synchronizes without touching the data plane.
    control_plane_round("met.done")
    print(f"METRICS_OK rank={rank}")


def scenario_trace(hvd):
    """hvd-trace acceptance (ISSUE 10): a seeded slow rank (rank 1
    pays a loader stall before each collective — the slow-loader
    scenario, instrumented exactly as the prefetch consumer
    instruments its blocked wait) across REAL processes.  Rank 0 then
    (a) merges the fleet trace — both ranks present, same-(step,
    cycle) negotiate spans OVERLAP after clock correction — and (b)
    runs the analyzer, which must attribute the stall to rank 1 with
    blame category ``host``.

    Control-plane-only traffic (the scenario_metrics trick:
    deliberately mismatched shapes negotiate fully, broadcast an ERROR
    and execute it on every rank with zero data-plane work), so this
    leg runs under any jax build."""
    import json as _json
    import time as _time

    import jax.numpy as jnp

    import horovod_tpu.trace as trace
    from horovod_tpu import HorovodError

    rank = hvd.rank()
    out = os.environ.get("HVD_TPU_TRACE_OUT",
                         "/tmp/hvd_fleet_trace.json")
    for step in range(1, 4):
        trace.set_step(step)
        if rank == 1:
            # The slow loader: a real stall on this rank's step path,
            # recorded as the host-leg span prefetch_to_device records
            # for its blocked consumer.
            t0 = _time.monotonic()
            _time.sleep(0.15)
            trace.span("prefetch.wait", "host", t0, _time.monotonic())
        try:
            hvd.allreduce(jnp.zeros((2 + rank,), jnp.float32),
                          name=f"tr.{step}", average=False)
            raise AssertionError("mismatched tr did not raise")
        except HorovodError as e:
            assert "Mismatched allreduce tensor shapes" in str(e), \
                str(e)
    _time.sleep(0.3)  # let the last broadcast's spans land everywhere

    if rank == 0:
        path = hvd.dump_fleet_trace(out, timeout=30.0)
        data = _json.load(open(path))
        evs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
        pids = {e["pid"] for e in evs}
        assert {0, 1} <= pids, pids
        # Clock alignment ran: a measured offset for the worker.
        assert "1" in data["metadata"]["clock_offsets_seconds"], \
            data["metadata"]
        # Same-(step, cycle) negotiate spans from BOTH ranks overlap
        # after clock correction — every rank's submit->execute window
        # contains the shared [last submit, broadcast] interval.
        windows = {}
        for e in evs:
            if e["cat"] != "negotiate":
                continue
            k = (e["args"]["step"], e["args"]["cycle"])
            lo, hi = e["ts"], e["ts"] + e["dur"]
            cur = windows.setdefault(k, {}).get(e["pid"])
            windows[k][e["pid"]] = (
                (lo, hi) if cur is None
                else (min(cur[0], lo), max(cur[1], hi)))
        shared = [k for k, d in windows.items() if {0, 1} <= set(d)]
        assert shared, windows
        overlaps = [k for k in shared
                    if windows[k][0][0] < windows[k][1][1]
                    and windows[k][1][0] < windows[k][0][1]]
        assert overlaps, (shared, windows)
        # The analyzer names the seeded slow rank with blame "host".
        from horovod_tpu.trace.analyze import analyze

        report = analyze(data["traceEvents"])
        host_blamed = [c for c in report["cycles"]
                       if c["straggler"] == 1 and c["blame"] == "host"]
        assert len(host_blamed) >= 3, report["cycles"]
        # Determinism (the CI trace-analysis gate): two replays of the
        # same merged file are byte-identical.
        a = _json.dumps(analyze(data["traceEvents"]), sort_keys=True)
        b = _json.dumps(analyze(data["traceEvents"]), sort_keys=True)
        assert a == b
    else:
        try:
            hvd.dump_fleet_trace(out)
            raise AssertionError("dump_fleet_trace must be rank-0-only")
        except RuntimeError as e:
            assert "rank-0" in str(e), str(e)
    # Barrier via a full-negotiation mismatch: keeps rank 1 alive (and
    # answering the FRAME_TRACE pull) until rank 0's merge finished.
    try:
        hvd.allreduce(jnp.zeros((2 + rank,), jnp.float32),
                      name="tr.done", average=False)
        raise AssertionError("mismatched tr.done did not raise")
    except HorovodError:
        pass
    print(f"TRACE_OK rank={rank}")


def scenario_combo(hvd):
    """Run several NON-DESTRUCTIVE scenarios sequentially in ONE launch
    (``HVD_TPU_COMBO`` names them, comma-separated).  Every separate
    launch pays full JAX init on every rank on the 1-core CI box, so
    batching the scenarios that leave the group healthy — collectives,
    mismatch validation, SPMD training, withdrawal recovery, stall
    recovery, checkpoint, torch/tf frontends — cuts the suite's
    wall-clock by minutes without losing any coverage: each scenario
    still prints its own marker for the test to assert."""
    for name in os.environ["HVD_TPU_COMBO"].split(","):
        globals()[f"scenario_{name}"](hvd)
    print(f"COMBO_OK rank={hvd.rank()}")


def main():
    scenario = sys.argv[1]
    import horovod_tpu as hvd

    hvd.init()
    try:
        globals()[f"scenario_{scenario}"](hvd)
    finally:
        if not getattr(main, "skip_shutdown", False):
            hvd.shutdown()


if __name__ == "__main__":
    main()
