"""hvd-trace: spans, clock alignment, fleet merge, analyzer, watcher.

Covers the ISSUE 10 tentpole in-process (the np=2 integration legs live
in tests/test_multiprocess.py) plus the satellites: the timeline's
strictly-valid-JSON close, the flight-recorder metrics tail, the trace
metrics on the exporter, and the clock-offset estimator under chaos
transport delay/dup with a reconnect re-convergence.
"""

import glob
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

THRESHOLD = 64 << 20


# ---------------------------------------------------------------------------
# Satellite: timeline emits strictly valid JSON; close() is idempotent
# under a concurrent instant() writer
# ---------------------------------------------------------------------------

def test_timeline_close_emits_strictly_valid_json(tmp_path):
    from horovod_tpu.utils.timeline import Timeline

    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.negotiate_start("t0", "allreduce")
    tl.negotiate_rank_ready("t0", 1)
    tl.negotiate_end("t0")
    tl.cache_counter(3, 1)
    tl.close()
    events = json.loads(open(path).read())  # parse-it-back: no comma
    assert isinstance(events, list) and len(events) >= 5
    assert events[-1]["name"] == "shutdown"


def test_timeline_empty_file_is_valid_json(tmp_path):
    from horovod_tpu.utils.timeline import Timeline

    path = str(tmp_path / "tl.json")
    Timeline(path).close()
    events = json.loads(open(path).read())
    assert [e["name"] for e in events] == ["shutdown"]


def test_timeline_close_idempotent_under_concurrent_instant(tmp_path):
    from horovod_tpu.utils.timeline import Timeline

    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            tl.instant("t", f"mark.{i}")  # post-close: silent no-op
            i += 1

    th = threading.Thread(target=hammer, daemon=True)
    th.start()
    time.sleep(0.05)
    tl.close()
    tl.close()  # idempotent
    stop.set()
    th.join(timeout=5.0)
    tl.instant("t", "after")  # still a no-op, still no crash
    events = json.loads(open(path).read())  # file stayed valid JSON
    assert events[-1]["name"] == "shutdown"


def test_timeline_events_carry_trace_context(tmp_path):
    import horovod_tpu.trace as trace
    from horovod_tpu.utils.timeline import Timeline

    trace.reset_run(rank=0)
    trace.set_step(7)
    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.negotiate_start("t0", "allreduce")
    tl.negotiate_end("t0")
    tl.close()
    events = json.loads(open(path).read())
    starts = [e for e in events if e.get("ph") == "B"]
    assert starts and starts[0]["args"]["step"] == 7
    assert "cycle" in starts[0]["args"]


# ---------------------------------------------------------------------------
# Satellite: flight dumps carry a compact metrics tail
# ---------------------------------------------------------------------------

def test_flight_dump_appends_metrics_tail(tmp_path, monkeypatch):
    import horovod_tpu.telemetry as tel
    from horovod_tpu.telemetry import flight

    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    tel.counter("collective.submitted").inc(0)  # ensure key exists
    flight.record("unit", "metrics-tail")
    path = flight.dump("metrics-tail-test")
    assert path is not None
    payload = json.loads(open(path).read())
    tail = payload["metrics"]
    assert "collective.submitted" in tail
    # Histograms compact to count+sum; counters/gauges to bare values.
    for v in tail.values():
        assert isinstance(v, (int, float, dict))
        if isinstance(v, dict):
            assert set(v) == {"count", "sum"}


def test_flight_metrics_provider_failure_never_breaks_dump(tmp_path,
                                                           monkeypatch):
    from horovod_tpu.telemetry import flight

    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    old = flight._metrics_provider
    flight.set_metrics_provider(lambda: 1 / 0)
    try:
        path = flight.dump("provider-broken")
        assert path is not None
        assert "metrics" not in json.loads(open(path).read())
    finally:
        flight.set_metrics_provider(old)


# ---------------------------------------------------------------------------
# Span buffer + context propagation
# ---------------------------------------------------------------------------

def test_span_buffer_records_context_and_counts():
    import horovod_tpu.telemetry as tel
    import horovod_tpu.trace as trace

    trace.reset_run(rank=0)
    trace.set_step(3)
    before = tel.metrics().get("trace.spans", {}).get("value", 0)
    t0 = time.monotonic()
    trace.span("unit.work", "host", t0, t0 + 0.001, args={"k": 1})
    evs = trace.export_events()
    assert evs[-1]["name"] == "unit.work"
    assert evs[-1]["args"]["step"] == 3
    assert evs[-1]["args"]["cycle"] == 0
    assert evs[-1]["args"]["k"] == 1
    assert evs[-1]["dur"] == pytest.approx(1000.0, rel=0.2)
    assert tel.metrics()["trace.spans"]["value"] == before + 1


def test_span_buffer_is_bounded_and_gated():
    import horovod_tpu.trace as trace

    trace.reset_run(rank=0)
    cap = trace._state._events.maxlen
    for i in range(cap + 50):
        trace.instant(f"e{i}", "host")
    assert len(trace.export_events()) == cap
    trace.set_enabled(False)
    try:
        n = len(trace.export_events())
        trace.instant("off", "host")
        assert len(trace.export_events()) == n  # disabled = no record
    finally:
        trace.set_enabled(True)


def test_ctx_trailer_roundtrip_and_response_list_compat():
    import horovod_tpu.trace as trace
    from horovod_tpu.ops import wire

    trace.reset_run(rank=0, trace_id=77)
    trace.set_step(5)
    trace.observe_ctx(5, 9, 77)
    resps = [wire.Response(wire.ResponseType.ALLREDUCE, ["x"],
                           devices=[-1], tensor_sizes=[])]
    payload = wire.pack_response_list(resps) + trace.pack_ctx()
    # Old parser: the self-delimiting list ignores the trailer.
    got = wire.unpack_response_list(payload)
    assert got[0].tensor_names == ["x"]
    # New parser: reads the trailer after the consumed offset.
    got2, off = wire.unpack_response_list_ex(payload)
    step, cycle, tid = trace.unpack_ctx(payload, off)
    assert (step, cycle, tid) == (5, 9, 77)
    # A trailer-less payload parses as no context, not garbage.
    assert trace.unpack_ctx(wire.pack_response_list(resps), off) is None


# ---------------------------------------------------------------------------
# Clock-offset estimation (unit + under chaos over real sockets)
# ---------------------------------------------------------------------------

def test_offset_estimator_min_rtt_filter():
    from horovod_tpu.trace.clock import OffsetEstimator

    est = OffsetEstimator()
    # True offset +2.0 s; clean sample (rtt 1 ms) vs delayed samples
    # whose asymmetric queueing skews the midpoint estimate badly.
    assert est.offset() is None and est.error_bound() is None
    est.add(10.0, 12.0505, 10.101)            # delayed: rtt ~101 ms
    est.add(20.0, 22.0005, 20.001)            # clean:   rtt   1 ms
    est.add(30.0, 32.0805, 30.161)            # delayed: rtt ~161 ms
    assert est.offset() == pytest.approx(2.0, abs=1e-3)
    assert est.error_bound() == pytest.approx(0.0005, abs=1e-4)
    assert est.count == 3
    est.reset()
    assert est.offset() is None


def test_offset_estimator_rejects_causally_impossible_samples():
    from horovod_tpu.trace.clock import OffsetEstimator

    est = OffsetEstimator()
    assert est.add(10.0, 12.0, 9.9) is None  # t2 < t0: replay artifact
    assert est.offset() is None


@pytest.fixture()
def cp_pair():
    """Controller + worker transport over loopback (the test_chaos
    harness shape) — enough control plane for ping/pong and FRAME_TRACE
    without a jax runtime."""
    from horovod_tpu.ops import transport as T
    from horovod_tpu.ops.coordinator import Coordinator

    if os.environ.get("HVD_TPU_NO_SOCKETS") == "1":
        pytest.skip("sandbox without loopback sockets")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD)
    holder = {}
    th = threading.Thread(
        target=lambda: holder.__setitem__(
            "ctrl", T.ControllerTransport(coord, 2, port)),
        daemon=True)
    th.start()
    time.sleep(0.1)
    worker = T.WorkerTransport("127.0.0.1", port, 1)
    th.join(timeout=10.0)
    ctrl = holder["ctrl"]
    yield ctrl, worker
    worker.close()
    ctrl.close()
    coord.close()


def _wait_offset(ctrl, rank=1, deadline=5.0):
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        offs = ctrl.clock.offsets()
        if rank in offs:
            return offs[rank]
        time.sleep(0.01)
    raise AssertionError(f"no clock offset for rank {rank}: "
                         f"{ctrl.clock.sample_counts()}")


def test_clock_offset_same_process_is_near_zero(cp_pair):
    """Both transports share one monotonic clock, so the estimate must
    sit near zero — and the per-peer gauge must be exported."""
    import horovod_tpu.telemetry as tel

    ctrl, _worker = cp_pair
    ctrl.measure_clock_offsets(probes=4, timeout=5.0)
    off = _wait_offset(ctrl)
    assert abs(off) < 0.05, off
    g = tel.metrics().get("trace.clock_offset_seconds.rank1")
    assert g is not None and abs(g["value"]) < 0.05, g


def test_clock_offset_bounded_under_chaos_delay_dup_and_reconnects(
        cp_pair, monkeypatch):
    """ISSUE 10 satellite: with transport delay + dup clauses armed the
    min-RTT filter keeps the estimate within bounds (true offset ~0
    in-process, injected delays are 80 ms), and after a hard
    connection reset + session resume the estimator RE-CONVERGES from
    a fresh window."""
    import horovod_tpu.chaos as chaos
    import horovod_tpu.telemetry as tel
    from horovod_tpu.ops import transport as T

    ctrl, worker = cp_pair
    monkeypatch.setenv(
        "HVD_TPU_FAULTS",
        "transport.delay:p=0.5:count=1000:delay=0.08;"
        "transport.dup:p=0.3:count=1000@11")
    chaos.reload()
    try:
        for _ in range(6):
            ctrl.ping_peers()
            time.sleep(0.02)
        off = _wait_offset(ctrl)
        # An unfiltered mean over 80 ms asymmetric delays would sit
        # tens of ms out; the min-RTT sample keeps it tight.
        assert abs(off) < 0.02, off
        counts0 = ctrl.clock.sample_counts().get(1, 0)
        assert counts0 >= 1

        before = tel.metrics().get("transport.reconnects",
                                   {}).get("value", 0)
        T._hard_close(worker._sock)  # the fault
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            now = tel.metrics().get("transport.reconnects",
                                    {}).get("value", 0)
            if now > before:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("worker never reconnected")
        # Resume reset the window; fresh probes re-converge it.
        for _ in range(6):
            ctrl.ping_peers()
            time.sleep(0.02)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if ctrl.clock.sample_counts().get(1, 0) >= 1 \
                    and 1 in ctrl.clock.offsets():
                break
            time.sleep(0.02)
        off2 = ctrl.clock.offsets()[1]
        assert abs(off2) < 0.02, off2
    finally:
        monkeypatch.delenv("HVD_TPU_FAULTS", raising=False)
        chaos.reload()


def test_controller_submit_gives_rank0_arrival_baseline(cp_pair):
    """The minimal real fleet (controller + ONE worker) must produce a
    live skew signal: rank 0's own submit stamps the cycle baseline,
    the worker's request-batch trailer stamps its arrival — without
    the rank-0 feed every cycle would have a single entry and
    StragglerWatch would be silently inert."""
    import horovod_tpu.trace as trace
    from horovod_tpu.ops import wire
    from horovod_tpu.trace import watch

    ctrl, worker = cp_pair
    trace.reset_run(rank=0)
    trace.set_step(2)
    watch.tracker.clear()

    def req(rank):
        return wire.Request(rank, wire.RequestType.ALLREDUCE,
                            wire.DataType.FLOAT32, "sk.x", -1, -1,
                            (4,), wire.ReduceOp.SUM, 0, ())

    ctrl.submit(req(0))           # rank 0: local, never on the wire
    worker.submit(req(1))
    worker.flush_requests()       # carries the trace trailer
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        skews = watch.tracker.skew_by_rank()
        if 0 in skews and 1 in skews:
            break
        time.sleep(0.01)
    else:
        raise AssertionError(watch.tracker.skew_by_rank())
    assert skews[0] == pytest.approx(0.0)   # rank 0 is the baseline
    assert skews[1] >= 0.0
    # Dedup: a second rank-0 submit in the same cycle adds nothing.
    assert watch.tracker.note(0, 2, 0, time.monotonic()) is False


def test_collect_traces_pulls_worker_buffer(cp_pair):
    import horovod_tpu.trace as trace

    ctrl, _worker = cp_pair
    trace.reset_run(rank=0)
    t0 = time.monotonic()
    trace.span("worker.side", "host", t0, t0 + 0.001)
    per_rank = ctrl.collect_traces([{"name": "ctrl.side"}], timeout=10.0)
    assert set(per_rank) == {0, 1}
    assert per_rank[0][0]["name"] == "ctrl.side"
    # The worker answered from ITS buffer (same process here, so the
    # span we just recorded is visible through the wire round trip).
    assert any(e.get("name") == "worker.side" for e in per_rank[1])


# ---------------------------------------------------------------------------
# Merge + analyzer
# ---------------------------------------------------------------------------

def _span(rank, name, cat, t0_us, dur_us, step, cycle, **extra):
    return {"name": name, "cat": cat, "ph": "X", "ts": float(t0_us),
            "dur": float(dur_us), "pid": rank,
            "args": {"step": step, "cycle": cycle, **extra}}


def _arrival(rank, t_us, step, cycle):
    return {"name": "BATCH_ARRIVAL", "cat": "negotiate", "ph": "i",
            "s": "t", "ts": float(t_us), "pid": 0,
            "args": {"step": step, "cycle": cycle, "rank": rank}}


def test_merge_events_applies_clock_offsets():
    from horovod_tpu.trace.merge import merge_events

    per_rank = {0: [{"name": "a", "cat": "dispatch", "ph": "X",
                     "ts": 1000.0, "dur": 10.0, "args": {}}],
                1: [{"name": "b", "cat": "dispatch", "ph": "X",
                     "ts": 501000.0, "dur": 10.0, "args": {}}]}
    merged = merge_events(per_rank, offsets={1: 0.5})  # rank1 +0.5 s
    xs = {e["pid"]: e for e in merged if e.get("ph") == "X"}
    assert xs[0]["ts"] == 1000.0
    assert xs[1]["ts"] == pytest.approx(1000.0)  # aligned onto rank 0
    names = [e for e in merged if e.get("ph") == "M"]
    assert any(e["name"] == "process_name" and e["pid"] == 1
               for e in names)


def _slow_rank_events():
    """Synthetic 2-rank fleet: rank 1 is input-bound — its prefetch
    wait delays every cycle's arrival."""
    evs = []
    for cycle in range(1, 4):
        step = 1
        base = cycle * 100_000.0
        # rank 1 stalls on its loader, then arrives late.
        evs.append(_span(1, "prefetch.wait", "host", base, 30_000.0,
                         step, cycle))
        evs.append(_arrival(0, base + 1_000.0, step, cycle))
        evs.append(_arrival(1, base + 31_000.0, step, cycle))
        for rank in (0, 1):
            evs.append(_span(rank, "negotiate.wait", "negotiate",
                             base + 1_000.0 + rank * 30_000.0,
                             31_000.0 - rank * 30_000.0, step, cycle))
            d0 = base + 32_000.0
            evs.append(_span(rank, "execute/allreduce", "dispatch",
                             d0, 5_000.0, step, cycle))
            evs.append(_span(rank, "megakernel/psum", "collective",
                             d0 + 1_000.0, 3_000.0, step, cycle,
                             wire_bytes=1000, dcn_bytes=250))
    return evs


def test_analyzer_names_slow_rank_and_category():
    from horovod_tpu.trace.analyze import analyze, render

    report = analyze(_slow_rank_events())
    assert report["ranks"] == [0, 1]
    # Every cycle's straggler is rank 1, blamed on its host leg.
    assert report["stragglers"] == {"1": 3}
    for c in report["cycles"]:
        assert c["straggler"] == 1, c
        assert c["blame"] == "host", c
        assert c["skew_us"] == pytest.approx(30_000.0)
    # The launch spans decompose: pack (1 ms) + unpack (1 ms) around a
    # 3 ms collective whose DCN share is 25%.
    attr = report["attribution_us"]
    assert attr["host"] == pytest.approx(3 * 30_000.0)
    assert attr["pack"] == pytest.approx(3 * 1_000.0)
    assert attr["unpack"] == pytest.approx(3 * 1_000.0)
    assert attr["dcn"] == pytest.approx(3 * 750.0)
    assert attr["collective"] == pytest.approx(3 * 2_250.0)
    text = render(report)
    assert "rank 1 led 3 cycle(s); dominant blame: host" in text


def test_analyzer_is_deterministic_across_replays(tmp_path):
    """The CI trace-analysis gate: two runs over one file are
    byte-identical."""
    from horovod_tpu.trace.analyze import analyze

    events = _slow_rank_events()
    a = json.dumps(analyze(events), sort_keys=True)
    b = json.dumps(analyze(list(events)), sort_keys=True)
    assert a == b


def test_analyzer_handles_bare_timeline_without_spans(tmp_path):
    from horovod_tpu.trace.analyze import analyze, load_trace

    path = tmp_path / "tl.json"
    path.write_text(json.dumps([{"ph": "B", "ts": 1, "pid": 0,
                                 "name": "NEGOTIATE_ALLREDUCE"}]))
    report = analyze(load_trace(str(path)))
    assert report["total_spans"] == 0
    assert report["cycles"] == []


def test_cli_reports_and_writes_json(tmp_path):
    trace_path = tmp_path / "fleet.json"
    trace_path.write_text(json.dumps(
        {"traceEvents": _slow_rank_events()}))
    out_json = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.trace", str(trace_path),
         "--json", str(out_json)],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr
    assert "dominant blame: host" in proc.stdout
    report = json.loads(out_json.read_text())
    assert report["stragglers"] == {"1": 3}


def test_cli_unparseable_file_exits_2(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.trace", str(bad)],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 2
    assert "error:" in proc.stderr


# ---------------------------------------------------------------------------
# StragglerWatch
# ---------------------------------------------------------------------------

def test_straggler_watch_fires_after_n_consecutive_steps(capfd):
    import horovod_tpu.telemetry as tel
    from horovod_tpu.trace.watch import SkewTracker, StragglerWatch

    w = StragglerWatch(threshold=0.01, patience=3,
                       tracker_=SkewTracker())
    before = tel.metrics().get("trace.straggler_warnings",
                               {}).get("value", 0)
    skews = {1: 0.002, 2: 0.05}
    assert w.check(skews) is None
    assert w.check(skews) is None
    fired = w.check(skews)
    assert fired is not None and fired[0]["rank"] == 2
    err = capfd.readouterr().err
    assert "rank 2" in err and "horovod_tpu.trace" in err
    assert tel.metrics()["trace.straggler_warnings"]["value"] == \
        before + 1
    # A healthy step resets the streak.
    assert w.check(skews) is None
    assert w.check({1: 0.002, 2: 0.001}) is None
    assert w.check(skews) is None
    assert w.check(skews) is None


def test_straggler_watch_names_every_simultaneous_straggler(capfd):
    from horovod_tpu.trace.watch import SkewTracker, StragglerWatch

    w = StragglerWatch(threshold=0.01, patience=2,
                       tracker_=SkewTracker())
    skews = {2: 0.05, 5: 0.09}
    assert w.check(skews) is None
    fired = w.check(skews)
    assert [f["rank"] for f in fired] == [2, 5]  # BOTH named
    err = capfd.readouterr().err
    assert "rank 2" in err and "rank 5" in err


def test_straggler_watch_reads_the_arrival_tracker():
    from horovod_tpu.trace.watch import SkewTracker, StragglerWatch

    tr = SkewTracker()
    t0 = 100.0
    for cycle in range(8):
        tr.note(0, 1, cycle, t0 + cycle)
        tr.note(1, 1, cycle, t0 + cycle + 0.2)  # rank 1 lags 200 ms
    skews = tr.skew_by_rank()
    assert skews[1] == pytest.approx(0.2)
    assert skews[0] == pytest.approx(0.0)
    w = StragglerWatch(threshold=0.1, patience=2, tracker_=tr)
    assert w.check() is None
    assert w.check()[0]["rank"] == 1


def test_straggler_watch_rejects_nonsense():
    from horovod_tpu.trace.watch import StragglerWatch

    with pytest.raises(ValueError):
        StragglerWatch(threshold=0.0)
    with pytest.raises(ValueError):
        StragglerWatch(patience=0)


# ---------------------------------------------------------------------------
# Exporter surface + single-process end-to-end
# ---------------------------------------------------------------------------

def test_trace_metrics_render_in_prometheus_text():
    import horovod_tpu.telemetry as tel
    import horovod_tpu.trace as trace
    from horovod_tpu.telemetry.exporter import prometheus_text

    trace.reset_run(rank=0)
    t0 = time.monotonic()
    trace.span("unit", "host", t0, t0)
    tel.gauge("trace.clock_offset_seconds.rank1").set(0.001)
    text = prometheus_text(tel.metrics())
    assert "hvd_trace_spans" in text
    assert "hvd_trace_clock_offset_seconds_rank1" in text
    assert "hvd_trace_straggler_warnings" in text


def test_single_process_fleet_trace_end_to_end(hvd2, tmp_path):
    """dump_fleet_trace + analyzer over a REAL (single-process) run:
    spans land with step/cycle context, merge writes a loadable file,
    the analyzer attributes the cycles."""
    import jax.numpy as jnp

    import horovod_tpu.trace as trace
    from horovod_tpu.trace.analyze import analyze, load_trace

    trace.set_step(4)
    for i in range(2):
        hvd2.allreduce(jnp.ones(8), average=False, name=f"tr.{i}")
    path = hvd2.dump_fleet_trace(str(tmp_path / "fleet.json"))
    data = json.load(open(path))
    assert data["metadata"]["format"] == "hvd-fleet-trace-v1"
    xs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert xs and all(e["pid"] == 0 for e in xs)
    assert {e["cat"] for e in xs} >= {"negotiate", "dispatch"}
    assert all(e["args"]["step"] == 4 for e in xs)
    report = analyze(load_trace(path))
    assert report["total_spans"] == len(xs)
    assert len(report["cycles"]) >= 1
    assert sum(report["attribution_us"].values()) > 0
