"""Gradient compression (hvd.Compression.{none,fp16,bf16}).

The reference snapshot predates Horovod's compression API; these tests
pin the contract Horovod later standardized: gradients are cast down for
the wire and restored after, the result keeps the original dtype, and
the compressed reduction stays within the wire dtype's tolerance of the
uncompressed one — on both the static (fused psum) and eager
(async-handle) paths.
"""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_api
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.parallel.training import make_train_step, shard_batch


def test_compress_roundtrip_dtypes():
    t = jnp.arange(8, dtype=jnp.float32) / 3.0
    for comp in (Compression.fp16, Compression.bf16):
        wire, ctx = comp.compress(t)
        assert wire.dtype == comp.wire_dtype
        back = comp.decompress(wire, ctx)
        assert back.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(back), np.asarray(t),
                                   rtol=1e-2)


def test_non_float_and_narrow_tensors_pass_through():
    idx = jnp.arange(8, dtype=jnp.int32)
    wire, ctx = Compression.bf16.compress(idx)
    assert wire.dtype == jnp.int32 and ctx is None
    half = jnp.ones((4,), jnp.bfloat16)
    wire, ctx = Compression.fp16.compress(half)
    assert wire.dtype == jnp.bfloat16 and ctx is None
    assert Compression.none.compress(idx) == (idx, None)


def _loss_fn(model):
    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)
    return loss_fn


@pytest.mark.parametrize("comp", [Compression.bf16, Compression.fp16])
def test_static_path_compressed_matches_uncompressed(hvd, comp):
    """Inside shard_map: compressed fused reduction ~= exact, and the
    updated parameters keep their f32 dtype."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    opt = optax.sgd(0.1)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    outs = []
    for compression in (None, comp):
        dopt = hvd_api.DistributedOptimizer(opt, compression=compression)
        step = make_train_step(_loss_fn(model), dopt, donate=False)
        p, _, _ = step(params, dopt.init(params), batch)
        outs.append(p)
    for exact, compressed in zip(jax.tree_util.tree_leaves(outs[0]),
                                 jax.tree_util.tree_leaves(outs[1])):
        assert compressed.dtype == exact.dtype
        # One SGD step at lr 0.1: wire-dtype error on the gradient only.
        np.testing.assert_allclose(np.asarray(compressed),
                                   np.asarray(exact), atol=5e-3)


def test_compressed_average_divides_after_decompress():
    """Averaging divides in the RESTORED dtype (f32) after decompress,
    matching the ZeRO-1 path's numerics — not in the narrow wire dtype
    (advisor round-3 finding).  A 5-replica mesh makes the two orders
    bit-distinguishable (division by 5 is inexact in bfloat16)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.data import allreduce_gradients

    hvd_api.init(devices=jax.devices()[:5])
    try:
        n = hvd_api.size()
        assert n == 5
        mesh = hvd_api.mesh()
        # Full 8-bit-mantissa value (255/128): the 5-way sum cannot be
        # held exactly in bf16, so sum/5 is inexact in bf16 but has a
        # closer f32 representation — the two division orders differ.
        g = jnp.full((n, 1, 4), 1.9921875, jnp.float32)

        def step(avg):
            def body(x):
                x = jnp.squeeze(x, 0)
                out = allreduce_gradients(
                    {"w": x}, average=avg,
                    compression=Compression.bf16)["w"]
                return out[None]
            return jax.jit(_compat.shard_map(
                body, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
                check_vma=False))

        avg = np.asarray(step(True)(hvd_api.shard(g)))[0, 0]
        summed = np.asarray(step(False)(hvd_api.shard(g)))[0, 0]
        # New order: decompress (exact bf16->f32) then divide in f32.
        expected = summed / np.float32(n)
        # Old order: divide the wire-dtype sum in bf16, then decompress.
        old = np.asarray((jnp.asarray(summed).astype(jnp.bfloat16)
                          / jnp.asarray(n, jnp.bfloat16))
                         .astype(jnp.float32))
        assert not np.array_equal(old, expected), "test lost its teeth"
        np.testing.assert_array_equal(avg, expected)
    finally:
        hvd_api.shutdown()


def test_eager_path_compressed_allreduce_average(hvd):
    """Eager DistributedOptimizer path: bf16-compressed grads still
    average to the exact value for exactly-representable inputs."""
    dopt = hvd_api.DistributedOptimizer(optax.sgd(1.0),
                                        compression=Compression.bf16)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    st = dopt.init(params)
    grads = {"w": jnp.full((4,), 2.0, jnp.float32)}  # exact in bf16
    updates, _ = dopt.update(grads, st, params)
    out = optax.apply_updates(params, updates)["w"]
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), -2.0)


def test_compression_composes_with_fusion_thresholds(hvd):
    """Bucketed and unbucketed compressed reductions agree exactly (the
    wire dtype is the same either way; bucketing is not a semantic
    change)."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    outs = []
    for threshold in (0, 1 << 26):
        dopt = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                            fusion_threshold=threshold,
                                            compression=Compression.bf16)
        step = make_train_step(_loss_fn(model), dopt, donate=False)
        p, _, _ = step(params, dopt.init(params), batch)
        outs.append(p)
    for a, b in zip(jax.tree_util.tree_leaves(outs[0]),
                    jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_torch_frontend_accepts_compression(hvd):
    """The torch frontend takes the same compression kwarg GPU Horovod
    scripts pass: wire is fp16, result restores the torch dtype, and the
    in-place variant writes back the decompressed value."""
    torch = pytest.importorskip("torch")
    import horovod_tpu.frontends.torch as thvd

    t = torch.full((4,), 3.0)
    out = thvd.allreduce(t, average=True, compression=thvd.Compression.fp16)
    assert out.dtype == torch.float32
    np.testing.assert_allclose(out.numpy(), 3.0)

    t2 = torch.full((4,), 5.0)
    thvd.allreduce_(t2, average=True, compression=thvd.Compression.bf16)
    np.testing.assert_allclose(t2.numpy(), 5.0)

    # poll-then-synchronize on a non-inplace compressed handle: poll must
    # not discard the decompression context (regression: poll used to pop
    # the entry, so synchronize returned the raw bf16 wire array).
    h = thvd.allreduce_async(torch.full((4,), 7.0), average=True,
                             compression=thvd.Compression.bf16)
    while not thvd.poll(h):
        pass
    out3 = thvd.synchronize(h)
    assert out3.dtype == torch.float32
    np.testing.assert_allclose(out3.numpy(), 7.0)

    # Same poll-then-synchronize sequence on an IN-PLACE compressed
    # handle (regression: poll's write-back used to pop the whole record,
    # so synchronize crashed on the raw bf16 wire array).
    t3 = torch.full((4,), 9.0)
    h2 = thvd.allreduce_async_(t3, average=True,
                               compression=thvd.Compression.bf16)
    while not thvd.poll(h2):
        pass
    np.testing.assert_allclose(t3.numpy(), 9.0)  # poll wrote back
    out4 = thvd.synchronize(h2)
    assert out4.dtype == torch.float32
    np.testing.assert_allclose(out4.numpy(), 9.0)

    model = torch.nn.Linear(2, 1, bias=False)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        compression=thvd.Compression.bf16)
    loss = model(torch.ones((2, 2))).sum()
    loss.backward()
    opt.step()  # hooks fired compressed allreduces; step must not raise


def test_tf_frontend_accepts_compression(hvd):
    tf = pytest.importorskip("tensorflow")
    import horovod_tpu.frontends.tensorflow as tfhvd

    out = tfhvd.allreduce(tf.constant([2.0, 4.0]), average=True,
                          compression=tfhvd.Compression.bf16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), [2.0, 4.0])

    # DistributedGradientTape takes the same kwarg (GPU Horovod parity).
    w = tf.Variable([[2.0]])
    with tfhvd.DistributedGradientTape(
            tf.GradientTape(), compression=tfhvd.Compression.fp16) as tape:
        loss = w * w
    (g,) = tape.gradient(loss, [w])
    assert g.dtype == tf.float32
    np.testing.assert_allclose(g.numpy(), [[4.0]])


def test_sparse_leaves_bypass_compression(hvd):
    """IndexedSlices exchange as an uncompressed allgather: indices must
    never be cast; gathered values keep their dtype."""
    from horovod_tpu.ops.sparse import IndexedSlices

    dopt = hvd_api.DistributedOptimizer(optax.sgd(1.0),
                                        compression=Compression.fp16)
    dense = jnp.zeros((4, 2), jnp.float32)
    params = {"emb": dense}
    st = dopt.init(params)
    grads = {"emb": IndexedSlices(values=jnp.ones((1, 2), jnp.float32),
                                  indices=jnp.array([1]),
                                  dense_shape=(4, 2))}
    updates, _ = dopt.update(grads, st, params)
    out = optax.apply_updates(params, updates)["emb"]
    assert out.dtype == jnp.float32
    # All 8 replicas contributed the same row; averaged update is -1.
    np.testing.assert_allclose(np.asarray(out)[1], -1.0)
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)
