"""Gradient compression (hvd.Compression.{none,fp16,bf16}).

The reference snapshot predates Horovod's compression API; these tests
pin the contract Horovod later standardized: gradients are cast down for
the wire and restored after, the result keeps the original dtype, and
the compressed reduction stays within the wire dtype's tolerance of the
uncompressed one — on both the static (fused psum) and eager
(async-handle) paths.
"""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_api
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.parallel.training import make_train_step, shard_batch


def test_compress_roundtrip_dtypes():
    t = jnp.arange(8, dtype=jnp.float32) / 3.0
    for comp in (Compression.fp16, Compression.bf16):
        wire, ctx = comp.compress(t)
        assert wire.dtype == comp.wire_dtype
        back = comp.decompress(wire, ctx)
        assert back.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(back), np.asarray(t),
                                   rtol=1e-2)


def test_non_float_and_narrow_tensors_pass_through():
    idx = jnp.arange(8, dtype=jnp.int32)
    wire, ctx = Compression.bf16.compress(idx)
    assert wire.dtype == jnp.int32 and ctx is None
    half = jnp.ones((4,), jnp.bfloat16)
    wire, ctx = Compression.fp16.compress(half)
    assert wire.dtype == jnp.bfloat16 and ctx is None
    assert Compression.none.compress(idx) == (idx, None)


def _loss_fn(model):
    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)
    return loss_fn


@pytest.mark.parametrize("comp", [Compression.bf16, Compression.fp16])
def test_static_path_compressed_matches_uncompressed(hvd, comp):
    """Inside shard_map: compressed fused reduction ~= exact, and the
    updated parameters keep their f32 dtype."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    opt = optax.sgd(0.1)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    outs = []
    for compression in (None, comp):
        dopt = hvd_api.DistributedOptimizer(opt, compression=compression)
        step = make_train_step(_loss_fn(model), dopt, donate=False)
        p, _, _ = step(params, dopt.init(params), batch)
        outs.append(p)
    for exact, compressed in zip(jax.tree_util.tree_leaves(outs[0]),
                                 jax.tree_util.tree_leaves(outs[1])):
        assert compressed.dtype == exact.dtype
        # One SGD step at lr 0.1: wire-dtype error on the gradient only.
        np.testing.assert_allclose(np.asarray(compressed),
                                   np.asarray(exact), atol=5e-3)


def test_compressed_average_divides_after_decompress():
    """Averaging divides in the RESTORED dtype (f32) after decompress,
    matching the ZeRO-1 path's numerics — not in the narrow wire dtype
    (advisor round-3 finding).  A 5-replica mesh makes the two orders
    bit-distinguishable (division by 5 is inexact in bfloat16)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.data import allreduce_gradients

    hvd_api.init(devices=jax.devices()[:5])
    try:
        n = hvd_api.size()
        assert n == 5
        mesh = hvd_api.mesh()
        # Full 8-bit-mantissa value (255/128): the 5-way sum cannot be
        # held exactly in bf16, so sum/5 is inexact in bf16 but has a
        # closer f32 representation — the two division orders differ.
        g = jnp.full((n, 1, 4), 1.9921875, jnp.float32)

        def step(avg):
            def body(x):
                x = jnp.squeeze(x, 0)
                out = allreduce_gradients(
                    {"w": x}, average=avg,
                    compression=Compression.bf16)["w"]
                return out[None]
            return jax.jit(_compat.shard_map(
                body, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
                check_vma=False))

        avg = np.asarray(step(True)(hvd_api.shard(g)))[0, 0]
        summed = np.asarray(step(False)(hvd_api.shard(g)))[0, 0]
        # New order: decompress (exact bf16->f32) then divide in f32.
        expected = summed / np.float32(n)
        # Old order: divide the wire-dtype sum in bf16, then decompress.
        old = np.asarray((jnp.asarray(summed).astype(jnp.bfloat16)
                          / jnp.asarray(n, jnp.bfloat16))
                         .astype(jnp.float32))
        assert not np.array_equal(old, expected), "test lost its teeth"
        np.testing.assert_array_equal(avg, expected)
    finally:
        hvd_api.shutdown()


def test_eager_path_compressed_allreduce_average(hvd):
    """Eager DistributedOptimizer path: bf16-compressed grads still
    average to the exact value for exactly-representable inputs."""
    dopt = hvd_api.DistributedOptimizer(optax.sgd(1.0),
                                        compression=Compression.bf16)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    st = dopt.init(params)
    grads = {"w": jnp.full((4,), 2.0, jnp.float32)}  # exact in bf16
    updates, _ = dopt.update(grads, st, params)
    out = optax.apply_updates(params, updates)["w"]
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), -2.0)


def test_compression_composes_with_fusion_thresholds(hvd):
    """Bucketed and unbucketed compressed reductions agree exactly (the
    wire dtype is the same either way; bucketing is not a semantic
    change)."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    outs = []
    for threshold in (0, 1 << 26):
        dopt = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                            fusion_threshold=threshold,
                                            compression=Compression.bf16)
        step = make_train_step(_loss_fn(model), dopt, donate=False)
        p, _, _ = step(params, dopt.init(params), batch)
        outs.append(p)
    for a, b in zip(jax.tree_util.tree_leaves(outs[0]),
                    jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_torch_frontend_accepts_compression(hvd):
    """The torch frontend takes the same compression kwarg GPU Horovod
    scripts pass: wire is fp16, result restores the torch dtype, and the
    in-place variant writes back the decompressed value."""
    torch = pytest.importorskip("torch")
    import horovod_tpu.frontends.torch as thvd

    t = torch.full((4,), 3.0)
    out = thvd.allreduce(t, average=True, compression=thvd.Compression.fp16)
    assert out.dtype == torch.float32
    np.testing.assert_allclose(out.numpy(), 3.0)

    t2 = torch.full((4,), 5.0)
    thvd.allreduce_(t2, average=True, compression=thvd.Compression.bf16)
    np.testing.assert_allclose(t2.numpy(), 5.0)

    # poll-then-synchronize on a non-inplace compressed handle: poll must
    # not discard the decompression context (regression: poll used to pop
    # the entry, so synchronize returned the raw bf16 wire array).
    h = thvd.allreduce_async(torch.full((4,), 7.0), average=True,
                             compression=thvd.Compression.bf16)
    while not thvd.poll(h):
        pass
    out3 = thvd.synchronize(h)
    assert out3.dtype == torch.float32
    np.testing.assert_allclose(out3.numpy(), 7.0)

    # Same poll-then-synchronize sequence on an IN-PLACE compressed
    # handle (regression: poll's write-back used to pop the whole record,
    # so synchronize crashed on the raw bf16 wire array).
    t3 = torch.full((4,), 9.0)
    h2 = thvd.allreduce_async_(t3, average=True,
                               compression=thvd.Compression.bf16)
    while not thvd.poll(h2):
        pass
    np.testing.assert_allclose(t3.numpy(), 9.0)  # poll wrote back
    out4 = thvd.synchronize(h2)
    assert out4.dtype == torch.float32
    np.testing.assert_allclose(out4.numpy(), 9.0)

    model = torch.nn.Linear(2, 1, bias=False)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        compression=thvd.Compression.bf16)
    loss = model(torch.ones((2, 2))).sum()
    loss.backward()
    opt.step()  # hooks fired compressed allreduces; step must not raise


def test_tf_frontend_accepts_compression(hvd):
    tf = pytest.importorskip("tensorflow")
    import horovod_tpu.frontends.tensorflow as tfhvd

    out = tfhvd.allreduce(tf.constant([2.0, 4.0]), average=True,
                          compression=tfhvd.Compression.bf16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), [2.0, 4.0])

    # DistributedGradientTape takes the same kwarg (GPU Horovod parity).
    w = tf.Variable([[2.0]])
    with tfhvd.DistributedGradientTape(
            tf.GradientTape(), compression=tfhvd.Compression.fp16) as tape:
        loss = w * w
    (g,) = tape.gradient(loss, [w])
    assert g.dtype == tf.float32
    np.testing.assert_allclose(g.numpy(), [[4.0]])


def test_sparse_leaves_bypass_compression(hvd):
    """IndexedSlices exchange as an uncompressed allgather: indices must
    never be cast; gathered values keep their dtype."""
    from horovod_tpu.ops.sparse import IndexedSlices

    dopt = hvd_api.DistributedOptimizer(optax.sgd(1.0),
                                        compression=Compression.fp16)
    dense = jnp.zeros((4, 2), jnp.float32)
    params = {"emb": dense}
    st = dopt.init(params)
    grads = {"emb": IndexedSlices(values=jnp.ones((1, 2), jnp.float32),
                                  indices=jnp.array([1]),
                                  dense_shape=(4, 2))}
    updates, _ = dopt.update(grads, st, params)
    out = optax.apply_updates(params, updates)["emb"]
    assert out.dtype == jnp.float32
    # All 8 replicas contributed the same row; averaged update is -1.
    np.testing.assert_allclose(np.asarray(out)[1], -1.0)
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)


# ---------------------------------------------------------------------------
# Quantized wire formats (ISSUE 6): registry, policy, standalone codec
# ---------------------------------------------------------------------------

from horovod_tpu.ops import compression as comp


def test_resolve_error_names_every_compressor():
    with pytest.raises(ValueError) as ei:
        comp.resolve("int7")
    msg = str(ei.value)
    for name in ("none", "fp16", "bf16", "int8", "int4"):
        assert name in msg, msg
    # And the registry resolves every advertised name.
    for name in comp.valid_names():
        assert comp.resolve(name) is not None


def test_quant_compressor_rejects_wrap_api():
    """int8/int4 cannot wrap a sum collective the way casts do; the
    error must point at the correct selection API."""
    with pytest.raises(ValueError, match="set_compression"):
        Compression.int8.compress(jnp.ones(4))
    with pytest.raises(ValueError, match="int4"):
        Compression.int4.compress(jnp.ones(4))


@pytest.mark.parametrize("codec", ["int8", "int4"])
def test_standalone_quantize_roundtrip(codec):
    rng = np.random.default_rng(0)
    t = jnp.asarray(rng.standard_normal((5, 37)).astype(np.float32))
    cls = comp.resolve(codec)
    wire_data, ctx = cls.quantize(t)
    back = cls.dequantize(wire_data, ctx)
    assert back.shape == t.shape and back.dtype == t.dtype
    # Error bounded by one (power-of-two) quantization step per block.
    step = 2.0 * np.abs(np.asarray(t)).max() / comp._levels(cls.bits)
    assert np.abs(np.asarray(back) - np.asarray(t)).max() <= step


def test_pack_int4_roundtrip():
    q = jnp.asarray(np.random.default_rng(1).integers(
        -7, 8, size=(3, 64)).astype(np.int8))
    np.testing.assert_array_equal(
        np.asarray(comp.unpack_int4(comp.pack_int4(q))), np.asarray(q))


def test_wire_pack_roundtrip():
    fmt = comp.wire_format("int8")
    rng = np.random.default_rng(2)
    rows = jnp.asarray(rng.standard_normal((4, 512)).astype(np.float32))
    q, s = comp.quantize_blocks(rows, fmt, comp.step_key(0, 0))
    w = comp.wire_pack(q, s, fmt)
    assert w.dtype == jnp.uint8
    assert w.shape[-1] == comp.wire_bytes_per_chunk(512, fmt)
    q2, s2 = comp.wire_unpack(w, 512, fmt)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    assert np.asarray(s).tobytes() == np.asarray(s2).tobytes()


def test_pow2_scales_are_exact_in_bf16():
    fmt = comp.wire_format("int8")
    rng = np.random.default_rng(3)
    rows = jnp.asarray((rng.standard_normal((2, 1024)) * 100)
                       .astype(np.float32))
    _, s = comp.quantize_blocks(rows, fmt, comp.step_key(0, 0))
    sf = np.asarray(s.astype(jnp.float32))
    nz = sf[sf > 0]
    # Every scale is a power of two → mantissa bits all zero → the
    # bfloat16 wire cast was lossless.
    m, _ = np.frexp(nz)
    assert np.all(m == 0.5)


def test_stochastic_rounding_unbiased():
    """floor(x + u8-dither) over many draws averages to x (the SR
    contract the convergence story rests on)."""
    fmt = comp.wire_format("int8")
    x = jnp.full((1, 256), 0.35, jnp.float32) * 2.0  # 0.7 of a step
    draws = []
    for tick in range(200):
        q, s = comp.quantize_blocks(x, fmt, comp.step_key(0, tick))
        draws.append(np.asarray(comp.dequantize_blocks(q, s, fmt))[0, 0])
    assert abs(np.mean(draws) - 0.7) < 0.02


def test_wire_format_applicability():
    # Quantization: floats only, above the min-elems floor.
    assert comp.wire_format_for("int8", np.float32, 1024).bits == 8
    assert comp.wire_format_for("int8", np.int32, 1024) is None
    assert comp.wire_format_for("int8", np.float32, 4) is None
    assert comp.wire_format_for("int4", jnp.bfloat16, 1024).bits == 4
    # Casts keep the dtype-narrowing rule.
    assert comp.wire_format_for("bf16", np.float32, 8).wire_dtype \
        == "bfloat16"
    assert comp.wire_format_for("bf16", jnp.bfloat16, 1024) is None
    assert comp.wire_format_for("none", np.float32, 1024) is None


def test_policy_precedence_and_process_sets(monkeypatch):
    monkeypatch.setenv(comp.DEFAULT_ENV, "bf16")
    try:
        # Env default applies without a policy.
        assert comp.policy_name_for("anything", 0) == "bf16"
        hvd_policy = comp.CompressionPolicy(
            default="int8",
            rules=[(r"embedding", "int4"), (r"\bln\b|bias", "none")],
            process_sets={3: "none"})
        assert hvd_policy.name_for("model.embedding.w", 0) == "int4"
        assert hvd_policy.name_for("model.ln.scale", 0) == "none"
        assert hvd_policy.name_for("dense.kernel", 0) == "int8"
        # Rules win over the per-set override; the override wins over
        # the default.
        assert hvd_policy.name_for("model.embedding.w", 3) == "int4"
        assert hvd_policy.name_for("dense.kernel", 3) == "none"
        # Typos fail at construction with the full name list.
        with pytest.raises(ValueError, match="int8"):
            comp.CompressionPolicy(default="int9")
        with pytest.raises(ValueError):
            comp.CompressionPolicy(rules=[("x", "bogus")])
    finally:
        comp.set_compression()


def test_set_compression_flushes_executor_state(hvd):
    from horovod_tpu.ops import megakernel as mk

    flushes0 = mk.stats.flushes
    comp.set_compression(default="int8")
    try:
        assert mk.stats.flushes > flushes0
        assert comp.policy_name_for("w", 0) == "int8"
    finally:
        comp.set_compression()
    assert comp.get_compression() is None


def test_validate_env_rejects_typos(monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int9")
    with pytest.raises(ValueError, match="HVD_TPU_COMPRESSION"):
        comp.validate_env()
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    monkeypatch.setenv("HVD_TPU_QUANT_ROUNDING", "sometimes")
    with pytest.raises(ValueError, match="ROUNDING"):
        comp.validate_env()
    monkeypatch.setenv("HVD_TPU_QUANT_ROUNDING", "nearest")
    monkeypatch.setenv("HVD_TPU_QUANT_BLOCK", "33")
    with pytest.raises(ValueError, match="even block"):
        comp.validate_env()
    monkeypatch.setenv("HVD_TPU_QUANT_BLOCK", "128")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "gzip")
    with pytest.raises(ValueError, match="HVD_TPU_DCN_COMPRESS"):
        comp.validate_env()
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "int4")
    comp.validate_env()  # all well-formed now


def test_validate_env_runs_at_init(monkeypatch):
    """A typo'd compressor must fail hvd.init(), not the first
    collective (the satellite fix: the old error was bare and late)."""
    import jax

    import horovod_tpu as hvd_api

    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int9")
    with pytest.raises(ValueError, match="expected one of"):
        hvd_api.init(devices=jax.devices())


def test_env_fingerprint_covers_spmd_knobs(monkeypatch):
    fp0 = comp.env_fingerprint()
    assert "HVD_TPU_COMPRESSION=<unset>" in fp0 \
        or "HVD_TPU_COMPRESSION=" in fp0
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    fp1 = comp.env_fingerprint()
    assert fp1 != fp0
    assert "HVD_TPU_COMPRESSION=int8" in fp1
    assert "HVD_TPU_VIRTUAL_SLICES=2" in fp1


def test_handshake_fingerprint_warning(monkeypatch, capsys):
    """The control-plane HELLO carries the env fingerprint; a divergent
    knob makes the controller print a WARNING naming the rank and the
    knob (the env-knob uniformity contract, validated not just
    documented)."""
    import struct

    from horovod_tpu.ops import transport as tp

    def hello_payload(fp: str) -> bytes:
        hb = b"host1"
        fpb = fp.encode("utf-8")
        return (struct.pack("<i", 3) + struct.pack("<H", len(hb)) + hb
                + struct.pack("<H", len(fpb)) + fpb)

    # Identical fingerprints: silent.
    tp._check_env_fingerprint(
        3, hello_payload(comp.env_fingerprint()), 11)
    assert "WARNING" not in capsys.readouterr().err

    # Divergent knob: warn, naming rank and knob with both values.
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    theirs = comp.env_fingerprint().replace(
        "HVD_TPU_COMPRESSION=none", "HVD_TPU_COMPRESSION=int8")
    tp._check_env_fingerprint(3, hello_payload(theirs), 11)
    err = capsys.readouterr().err
    assert "WARNING" in err and "rank 3" in err
    assert "HVD_TPU_COMPRESSION" in err and "int8" in err

    # Pre-fingerprint HELLO (short payload): tolerated silently.
    tp._check_env_fingerprint(1, struct.pack("<i", 1), 4)
    assert "WARNING" not in capsys.readouterr().err
