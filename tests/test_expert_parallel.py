"""Mixture-of-Experts / expert-parallel tests.

Parity check: the expert-sharded layer (tokens exchanged with all_to_all)
must reproduce the single-group computation when capacity is ample, and
degrade only by dropping when it is not."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import EXPERT_AXIS, make_mesh
from horovod_tpu.parallel.expert import (MoEOutput, init_moe_params,
                                         local_experts, moe_layer)

TOL = 1e-4
E, D, H = 8, 16, 32


def _inputs(tokens=64, seed=0):
    key = jax.random.PRNGKey(seed)
    kx, kp = jax.random.split(key)
    x = jax.random.normal(kx, (tokens, D))
    params = init_moe_params(kp, E, D, H)
    return x, params


def _run(n_devices, x, params, **kw):
    mesh = make_mesh(expert=n_devices, devices=jax.devices()[:n_devices])

    def f(x, params):
        mine = local_experts(params, axis_name=EXPERT_AXIS)
        return moe_layer(x, mine, axis_name=EXPERT_AXIS, num_experts=E,
                         **kw)

    return jax.jit(_compat.shard_map(
        f, mesh=mesh, in_specs=(P(EXPERT_AXIS), P()),
        out_specs=MoEOutput(P(EXPERT_AXIS), P(), P()),
        check_vma=False))(x, params)


@pytest.mark.parametrize("top_k", [1, 2])
def test_sharded_matches_single_group(top_k):
    x, params = _inputs()
    # Ample capacity: nothing drops, so 1-group and 4-group answers agree.
    kw = dict(top_k=top_k, capacity_factor=8.0)
    out1, aux1, drop1 = _run(1, x, params, **kw)
    out4, aux4, drop4 = _run(4, x, params, **kw)
    assert float(drop1) == 0.0
    assert float(drop4) == 0.0
    # Fetch to host: the two runs live on different meshes.
    import numpy as np
    assert np.max(np.abs(np.asarray(out1) - np.asarray(out4))) < TOL


def test_moe_output_is_gated_expert_mix():
    # With top_k = E and huge capacity every expert fires: the output must
    # equal the dense mixture sum_e p_e * expert_e(x).
    x, params = _inputs(tokens=32)
    out, _, drop = _run(1, x, params, top_k=E, capacity_factor=float(E))
    assert float(drop) == 0.0
    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    h = jnp.einsum("td,edh->teh", x, params["w_in"])
    dense = jnp.einsum("teh,ehd->ted", jax.nn.gelu(h), params["w_out"])
    want = jnp.einsum("ted,te->td", dense, probs)
    assert jnp.max(jnp.abs(out - want)) < TOL


def test_capacity_drops_tokens():
    x, params = _inputs(tokens=64)
    _, _, drop = _run(1, x, params, top_k=1, capacity_factor=0.25)
    assert float(drop) > 0.0


def test_aux_loss_is_finite_and_positive():
    x, params = _inputs()
    _, aux, _ = _run(4, x, params, top_k=2, capacity_factor=4.0)
    assert bool(jnp.isfinite(aux))
    assert float(aux) > 0.0


def test_moe_gradients_flow_to_all_param_groups():
    x, params = _inputs(tokens=32)
    mesh = make_mesh(expert=4, devices=jax.devices()[:4])

    sm = jax.jit(_compat.shard_map(
        lambda x, params: moe_layer(
            x, local_experts(params, axis_name=EXPERT_AXIS),
            axis_name=EXPERT_AXIS, num_experts=E, top_k=2,
            capacity_factor=4.0),
        mesh=mesh, in_specs=(P(EXPERT_AXIS), P()),
        out_specs=MoEOutput(P(EXPERT_AXIS), P(), P()),
        check_vma=False))

    def loss(params):
        out, aux, _ = sm(x, params)
        return jnp.sum(out ** 2) + aux

    grads = jax.jit(jax.grad(loss))(params)
    for name, g in grads.items():
        assert bool(jnp.any(g != 0)), f"no gradient reached {name}"
        assert bool(jnp.all(jnp.isfinite(g)))
