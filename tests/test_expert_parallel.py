"""Mixture-of-Experts / expert-parallel tests.

Parity check: the expert-sharded layer (tokens exchanged with all_to_all)
must reproduce the single-group computation when capacity is ample, and
degrade only by dropping when it is not."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import EXPERT_AXIS, make_mesh
from horovod_tpu.parallel.expert import (MoEOutput, init_moe_params,
                                         local_experts, moe_layer)

TOL = 1e-4
E, D, H = 8, 16, 32


def _inputs(tokens=64, seed=0):
    key = jax.random.PRNGKey(seed)
    kx, kp = jax.random.split(key)
    x = jax.random.normal(kx, (tokens, D))
    params = init_moe_params(kp, E, D, H)
    return x, params


def _run(n_devices, x, params, **kw):
    mesh = make_mesh(expert=n_devices, devices=jax.devices()[:n_devices])

    def f(x, params):
        mine = local_experts(params, axis_name=EXPERT_AXIS)
        return moe_layer(x, mine, axis_name=EXPERT_AXIS, num_experts=E,
                         **kw)

    return jax.jit(_compat.shard_map(
        f, mesh=mesh, in_specs=(P(EXPERT_AXIS), P()),
        out_specs=MoEOutput(P(EXPERT_AXIS), P(), P()),
        check_vma=False))(x, params)


@pytest.mark.parametrize("top_k", [1, 2])
def test_sharded_matches_single_group(top_k):
    x, params = _inputs()
    # Ample capacity: nothing drops, so 1-group and 4-group answers agree.
    kw = dict(top_k=top_k, capacity_factor=8.0)
    out1, aux1, drop1 = _run(1, x, params, **kw)
    out4, aux4, drop4 = _run(4, x, params, **kw)
    assert float(drop1) == 0.0
    assert float(drop4) == 0.0
    # Fetch to host: the two runs live on different meshes.
    import numpy as np
    assert np.max(np.abs(np.asarray(out1) - np.asarray(out4))) < TOL


def test_moe_output_is_gated_expert_mix():
    # With top_k = E and huge capacity every expert fires: the output must
    # equal the dense mixture sum_e p_e * expert_e(x).
    x, params = _inputs(tokens=32)
    out, _, drop = _run(1, x, params, top_k=E, capacity_factor=float(E))
    assert float(drop) == 0.0
    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    h = jnp.einsum("td,edh->teh", x, params["w_in"])
    dense = jnp.einsum("teh,ehd->ted", jax.nn.gelu(h), params["w_out"])
    want = jnp.einsum("ted,te->td", dense, probs)
    assert jnp.max(jnp.abs(out - want)) < TOL


def test_capacity_drops_tokens():
    x, params = _inputs(tokens=64)
    _, _, drop = _run(1, x, params, top_k=1, capacity_factor=0.25)
    assert float(drop) > 0.0


def test_aux_loss_is_finite_and_positive():
    x, params = _inputs()
    _, aux, _ = _run(4, x, params, top_k=2, capacity_factor=4.0)
    assert bool(jnp.isfinite(aux))
    assert float(aux) > 0.0


# ---------------------------------------------------------------------------
# _top_k_dispatch edge cases (hvd-fuse satellite): pinned BEFORE the
# fused rewrite — the routing arithmetic is the part the chunked hot
# path must preserve exactly, so these run against the function
# directly (no mesh, no collectives).
# ---------------------------------------------------------------------------

def test_top_k_dispatch_capacity_one_admits_first_token_only():
    from horovod_tpu.parallel.expert import _top_k_dispatch

    # Both tokens prefer expert 0; capacity 1 admits only the earlier
    # token (cumsum order) and drops the other.
    probs = jnp.asarray([[0.9, 0.1],
                         [0.8, 0.2]], jnp.float32)
    dispatch, combine, dropped = _top_k_dispatch(probs, k=1, capacity=1)
    assert dispatch.shape == (2, 2, 1)
    assert float(dispatch[0, 0, 0]) == 1.0   # token 0 admitted
    assert float(dispatch[1, 0, 0]) == 0.0   # token 1 over capacity
    assert float(jnp.sum(dispatch[1])) == 0.0
    # Combine carries the gate for the admitted token only.
    assert float(combine[0, 0, 0]) == pytest.approx(0.9)
    assert float(jnp.sum(combine[1])) == 0.0
    assert float(dropped) == pytest.approx(0.5)


def test_top_k_dispatch_all_dropped_token_contributes_zero():
    from horovod_tpu.parallel.expert import _top_k_dispatch

    # Three tokens all racing for expert 0 at capacity 1 with k=1:
    # tokens 1 and 2 lose every round — their dispatch AND combine rows
    # must be exactly zero (the all-dropped token's output is zero, not
    # stale buffer content).
    probs = jnp.asarray([[0.99, 0.01],
                         [0.98, 0.02],
                         [0.97, 0.03]], jnp.float32)
    dispatch, combine, dropped = _top_k_dispatch(probs, k=1, capacity=1)
    assert float(jnp.sum(dispatch[1])) == 0.0
    assert float(jnp.sum(dispatch[2])) == 0.0
    assert float(jnp.sum(combine[1])) == 0.0
    assert float(jnp.sum(combine[2])) == 0.0
    assert float(dropped) == pytest.approx(2.0 / 3.0)


def test_top_k_dispatch_top_k_equals_num_experts():
    from horovod_tpu.parallel.expert import _top_k_dispatch

    # k == E with ample capacity: every token reaches every expert
    # exactly once, each expert's buffer slots fill without collision
    # (admission order interleaves the k greedy rounds, so positions
    # are a permutation of the slots, not token order), and the combine
    # weights are the full softmax row (sum = 1 per token).
    tokens, experts, capacity = 4, 3, 4
    key = jax.random.PRNGKey(3)
    probs = jax.nn.softmax(jax.random.normal(key, (tokens, experts)),
                           axis=-1)
    dispatch, combine, dropped = _top_k_dispatch(probs, k=experts,
                                                 capacity=capacity)
    assert float(dropped) == 0.0
    # One slot per (token, expert) pair.
    per_pair = jnp.sum(dispatch, axis=-1)
    assert bool(jnp.all(per_pair == 1.0))
    # Every expert buffer fills its slots exactly once (a permutation).
    pos = jnp.argmax(dispatch, axis=-1)  # [t, E]
    for e in range(experts):
        assert sorted(int(p) for p in pos[:, e]) == list(range(tokens))
    # Combine weight per (token, expert) is that pair's gate.
    gates = jnp.sum(combine, axis=-1)
    assert jnp.max(jnp.abs(gates - probs)) < 1e-6
    assert bool(jnp.all(jnp.abs(jnp.sum(gates, axis=-1) - 1.0) < 1e-6))


def test_moe_gradients_flow_to_all_param_groups():
    x, params = _inputs(tokens=32)
    mesh = make_mesh(expert=4, devices=jax.devices()[:4])

    sm = jax.jit(_compat.shard_map(
        lambda x, params: moe_layer(
            x, local_experts(params, axis_name=EXPERT_AXIS),
            axis_name=EXPERT_AXIS, num_experts=E, top_k=2,
            capacity_factor=4.0),
        mesh=mesh, in_specs=(P(EXPERT_AXIS), P()),
        out_specs=MoEOutput(P(EXPERT_AXIS), P(), P()),
        check_vma=False))

    def loss(params):
        out, aux, _ = sm(x, params)
        return jnp.sum(out ** 2) + aux

    grads = jax.jit(jax.grad(loss))(params)
    for name, g in grads.items():
        assert bool(jnp.any(g != 0)), f"no gradient reached {name}"
        assert bool(jnp.all(jnp.isfinite(g)))
