"""GPipe pipeline-parallel tests: the pipelined schedule must reproduce
sequential layer application, forward and backward."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import PIPE_AXIS, make_mesh
from horovod_tpu.parallel.pipeline import (gpipe, select_stage_params,
                                           stage_index)

TOL = 1e-5


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stacked_params(n_stages, d, seed=0):
    key = jax.random.PRNGKey(seed)
    kw, kb = jax.random.split(key)
    w = jax.random.normal(kw, (n_stages, d, d)) * (d ** -0.5)
    b = jax.random.normal(kb, (n_stages, d)) * 0.1
    return w, b


def _sequential(params, x):
    w, b = params
    for s in range(w.shape[0]):
        x = _stage_fn((w[s], b[s]), x)
    return x


@pytest.mark.parametrize("n_stages,n_micro", [(2, 2), (4, 4), (4, 8)])
def test_gpipe_matches_sequential(n_stages, n_micro):
    mesh = make_mesh(pipe=n_stages, devices=jax.devices()[:n_stages])
    d = 8
    params = _stacked_params(n_stages, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, d))

    def run(params, x):
        mine = select_stage_params(params)
        return gpipe(_stage_fn, mine, x, num_microbatches=n_micro)

    got = jax.jit(_compat.shard_map(run, mesh=mesh, in_specs=(P(), P()),
                                out_specs=P(), check_vma=False))(params, x)
    want = _sequential(params, x)
    assert jnp.max(jnp.abs(got - want)) < TOL


def test_gpipe_gradients_match_sequential():
    n_stages, n_micro = 4, 4
    mesh = make_mesh(pipe=n_stages, devices=jax.devices()[:n_stages])
    d = 8
    params = _stacked_params(n_stages, d, seed=2)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, d))

    sm = _compat.shard_map(
        lambda params, x: gpipe(_stage_fn, select_stage_params(params), x,
                                num_microbatches=n_micro),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    got = jax.jit(jax.grad(lambda p: jnp.sum(sm(p, x) ** 2)))(params)
    want = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_gpipe_rejects_indivisible_microbatches():
    mesh = make_mesh(pipe=2, devices=jax.devices()[:2])
    params = _stacked_params(2, 4)
    x = jnp.zeros((6, 4))
    sm = _compat.shard_map(
        lambda params, x: gpipe(_stage_fn, select_stage_params(params), x,
                                num_microbatches=4),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    with pytest.raises(ValueError, match="not divisible"):
        sm(params, x)


def test_stage_index():
    mesh = make_mesh(pipe=4, devices=jax.devices()[:4])
    out = jax.jit(_compat.shard_map(lambda: stage_index()[None], mesh=mesh,
                                in_specs=(), out_specs=P(PIPE_AXIS),
                                check_vma=False))()
    assert list(out) == [0, 1, 2, 3]


def test_gpipe_composes_with_data_parallel():
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    d = 8
    params = _stacked_params(2, d, seed=4)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, d))

    def run(params, x):
        mine = select_stage_params(params)
        return gpipe(_stage_fn, mine, x, num_microbatches=2)

    got = jax.jit(_compat.shard_map(run, mesh=mesh, in_specs=(P(), P("data")),
                                out_specs=P("data"),
                                check_vma=False))(params, x)
    want = _sequential(params, x)
    assert jnp.max(jnp.abs(got - want)) < TOL
