"""Pipeline-parallel tests.

Half one: the GPipe scan must reproduce sequential layer application,
forward and backward (one compiled program over the pipe axis).

Half two: the host-scheduled 1F1B MPMD rebuild (ISSUE 12) — the
dryrun schedule plan (dependency-valid ticks, bounded activation
memory, interleave shrinking the bubble), the per-stage-executable
train step (bitwise 1f1b ≡ gpipe-ordered dispatch, allclose vs the
monolithic mean-loss gradient), streamed partial-cycle reduction
riding the response cache, schedule-shape validation naming the axis
and the nearest valid counts, and the env knobs.
"""

import os

import jax
import numpy as np
import optax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.parallel.pipeline as PL
from horovod_tpu.core.topology import PIPE_AXIS, make_mesh
from horovod_tpu.parallel.pipeline import (gpipe, make_pipeline_train_step,
                                           schedule_plan,
                                           select_stage_params,
                                           stage_index)

TOL = 1e-5


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stacked_params(n_stages, d, seed=0):
    key = jax.random.PRNGKey(seed)
    kw, kb = jax.random.split(key)
    w = jax.random.normal(kw, (n_stages, d, d)) * (d ** -0.5)
    b = jax.random.normal(kb, (n_stages, d)) * 0.1
    return w, b


def _sequential(params, x):
    w, b = params
    for s in range(w.shape[0]):
        x = _stage_fn((w[s], b[s]), x)
    return x


@pytest.mark.parametrize("n_stages,n_micro", [(2, 2), (4, 4), (4, 8)])
def test_gpipe_matches_sequential(n_stages, n_micro):
    mesh = make_mesh(pipe=n_stages, devices=jax.devices()[:n_stages])
    d = 8
    params = _stacked_params(n_stages, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, d))

    def run(params, x):
        mine = select_stage_params(params)
        return gpipe(_stage_fn, mine, x, num_microbatches=n_micro)

    got = jax.jit(_compat.shard_map(run, mesh=mesh, in_specs=(P(), P()),
                                out_specs=P(), check_vma=False))(params, x)
    want = _sequential(params, x)
    assert jnp.max(jnp.abs(got - want)) < TOL


def test_gpipe_gradients_match_sequential():
    n_stages, n_micro = 4, 4
    mesh = make_mesh(pipe=n_stages, devices=jax.devices()[:n_stages])
    d = 8
    params = _stacked_params(n_stages, d, seed=2)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, d))

    sm = _compat.shard_map(
        lambda params, x: gpipe(_stage_fn, select_stage_params(params), x,
                                num_microbatches=n_micro),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    got = jax.jit(jax.grad(lambda p: jnp.sum(sm(p, x) ** 2)))(params)
    want = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_gpipe_rejects_indivisible_microbatches():
    mesh = make_mesh(pipe=2, devices=jax.devices()[:2])
    params = _stacked_params(2, 4)
    x = jnp.zeros((6, 4))
    sm = _compat.shard_map(
        lambda params, x: gpipe(_stage_fn, select_stage_params(params), x,
                                num_microbatches=4),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    with pytest.raises(ValueError, match="not divisible"):
        sm(params, x)


def test_stage_index():
    mesh = make_mesh(pipe=4, devices=jax.devices()[:4])
    out = jax.jit(_compat.shard_map(lambda: stage_index()[None], mesh=mesh,
                                in_specs=(), out_specs=P(PIPE_AXIS),
                                check_vma=False))()
    assert list(out) == [0, 1, 2, 3]


def test_gpipe_composes_with_data_parallel():
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    d = 8
    params = _stacked_params(2, d, seed=4)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, d))

    def run(params, x):
        mine = select_stage_params(params)
        return gpipe(_stage_fn, mine, x, num_microbatches=2)

    got = jax.jit(_compat.shard_map(run, mesh=mesh, in_specs=(P(), P("data")),
                                out_specs=P("data"),
                                check_vma=False))(params, x)
    want = _sequential(params, x)
    assert jnp.max(jnp.abs(got - want)) < TOL


def test_gpipe_error_names_axis_and_nearest_counts():
    """The indivisible-batch error names the axis size and suggests the
    nearest valid microbatch counts (divisors of the batch)."""
    mesh = make_mesh(pipe=2, devices=jax.devices()[:2])
    params = _stacked_params(2, 4)
    x = jnp.zeros((6, 4))
    sm = _compat.shard_map(
        lambda params, x: gpipe(_stage_fn, select_stage_params(params), x,
                                num_microbatches=4),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    with pytest.raises(ValueError) as ei:
        sm(params, x)
    msg = str(ei.value)
    assert "size 6" in msg and "num_microbatches=4" in msg
    assert "3 or 6" in msg  # nearest divisors of 6 around 4


def test_select_stage_params_pytree():
    """Direct unit test (previously only exercised through the
    transformer example): slicing a stacked pytree of dicts per stage."""
    mesh = make_mesh(pipe=4, devices=jax.devices()[:4])
    stacked = {"w": jnp.arange(4 * 3).reshape(4, 3).astype(jnp.float32),
               "b": jnp.arange(4.0)}
    out = jax.jit(_compat.shard_map(
        lambda p: select_stage_params(p)["w"][None],
        mesh=mesh, in_specs=(P(),), out_specs=P(PIPE_AXIS),
        check_vma=False))(stacked)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(stacked["w"]))
    outb = jax.jit(_compat.shard_map(
        lambda p: select_stage_params(p)["b"][None],
        mesh=mesh, in_specs=(P(),), out_specs=P(PIPE_AXIS),
        check_vma=False))(stacked)
    np.testing.assert_array_equal(np.asarray(outb).ravel(),
                                  np.asarray(stacked["b"]))


# ---------------------------------------------------------------------------
# The 1F1B MPMD schedule plan (the dryrun surface: no hardware, no jax)
# ---------------------------------------------------------------------------

def _check_plan_valid(plan):
    """Every dependency points to an EARLIER tick, and the plan fires
    exactly one forward and one backward per (stage, microbatch)."""
    S, m = plan.n_stages, plan.num_microbatches
    fwd_tick, bwd_tick = {}, {}
    for t, tick in enumerate(plan.ticks):
        for a in tick:
            if a.phase == "F":
                assert (a.stage, a.mb) not in fwd_tick
                fwd_tick[(a.stage, a.mb)] = t
                if a.stage > 0:
                    assert fwd_tick[(a.stage - 1, a.mb)] < t
            else:
                assert (a.stage, a.mb) not in bwd_tick
                bwd_tick[(a.stage, a.mb)] = t
                assert fwd_tick[(a.stage, a.mb)] < t
                if a.stage < S - 1:
                    assert bwd_tick[(a.stage + 1, a.mb)] < t
    assert set(fwd_tick) == {(s, i) for s in range(S) for i in range(m)}
    assert set(bwd_tick) == set(fwd_tick)
    # Backwards execute in microbatch order at EVERY stage — the
    # bitwise gradient-accumulation contract between schedules.
    for s in range(S):
        ticks = [bwd_tick[(s, i)] for i in range(m)]
        assert ticks == sorted(ticks)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("S,m,v", [(2, 2, 1), (4, 8, 1), (4, 8, 2),
                                   (4, 4, 4), (8, 4, 2), (3, 5, 1)])
def test_schedule_plan_valid(schedule, S, m, v):
    if S % v != 0:
        pytest.skip("interleave must divide stages")
    _check_plan_valid(schedule_plan(S, m, schedule, v))


def test_schedule_plan_1f1b_bounds_activation_memory():
    """1F1B's reason to exist at equal bubble: in-flight stage-boundary
    activations bounded by the stage depth, while GPipe grows with the
    microbatch count."""
    f = schedule_plan(4, 16, "1f1b")
    g = schedule_plan(4, 16, "gpipe")
    assert g.peak_activations == (4 - 1) * 16
    assert f.peak_activations <= 3 * 4
    assert f.peak_activations < g.peak_activations


def test_schedule_plan_interleave_shrinks_bubble():
    """Interleaved virtual stages fill the ramp: at a fixed executor
    count, splitting the model into more round-robin chunks lowers the
    idle fraction (arXiv:2412.14374's interleaved-1F1B claim, gated
    structurally with no hardware)."""
    flat = schedule_plan(4, 8, "1f1b", interleave=1)
    inter = schedule_plan(4, 8, "1f1b", interleave=2)
    assert inter.bubble_fraction < flat.bubble_fraction
    # Same comparison at a fixed FOUR-executor fleet: 8 chunks over 4
    # executors vs 4 stages over 4 executors.
    flat4 = schedule_plan(4, 4, "1f1b", interleave=1)
    inter4 = schedule_plan(8, 4, "1f1b", interleave=2)
    assert flat4.n_executors == inter4.n_executors == 4
    assert inter4.bubble_fraction < flat4.bubble_fraction


def test_schedule_plan_validation():
    with pytest.raises(ValueError, match="does not divide"):
        schedule_plan(4, 8, "1f1b", interleave=3)
    with pytest.raises(ValueError, match="nearest valid interleave"):
        schedule_plan(6, 8, "1f1b", interleave=4)
    with pytest.raises(ValueError, match="expected one of"):
        schedule_plan(4, 8, "zigzag")
    with pytest.raises(ValueError, match=">= 1"):
        schedule_plan(0, 8)


def test_pipeline_env_knobs(monkeypatch):
    monkeypatch.setenv(PL.SCHEDULE_ENV, "bogus")
    with pytest.raises(ValueError, match="HVD_TPU_PIPELINE_SCHEDULE"):
        PL.validate_env()
    monkeypatch.setenv(PL.SCHEDULE_ENV, "gpipe")
    monkeypatch.setenv(PL.INTERLEAVE_ENV, "x")
    with pytest.raises(ValueError, match="HVD_TPU_PIPELINE_INTERLEAVE"):
        PL.validate_env()
    monkeypatch.setenv(PL.INTERLEAVE_ENV, "2")
    PL.validate_env()
    assert schedule_plan(4, 4).schedule == "gpipe"
    assert schedule_plan(4, 4).interleave == 2
    monkeypatch.delenv(PL.SCHEDULE_ENV)
    monkeypatch.delenv(PL.INTERLEAVE_ENV)
    assert schedule_plan(4, 4).schedule == "1f1b"


def test_pipeline_knobs_in_hello_env_fingerprint(monkeypatch):
    """The schedule knobs select the dispatch order of compiled
    programs — they ride the HELLO env fingerprint like the overlap
    knob."""
    from horovod_tpu.ops import compression as compression_mod

    assert "HVD_TPU_PIPELINE_SCHEDULE" in compression_mod._SPMD_ENV_KNOBS
    assert "HVD_TPU_PIPELINE_INTERLEAVE" in compression_mod._SPMD_ENV_KNOBS
    monkeypatch.setenv(PL.SCHEDULE_ENV, "1f1b")
    fp_a = compression_mod.env_fingerprint()
    monkeypatch.setenv(PL.SCHEDULE_ENV, "gpipe")
    fp_b = compression_mod.env_fingerprint()
    assert fp_a != fp_b


def test_init_rejects_malformed_pipeline_env(monkeypatch):
    import horovod_tpu as H

    monkeypatch.setenv(PL.SCHEDULE_ENV, "sideways")
    with pytest.raises(ValueError, match="HVD_TPU_PIPELINE_SCHEDULE"):
        H.init(devices=jax.devices())


# ---------------------------------------------------------------------------
# The MPMD pipeline train step
# ---------------------------------------------------------------------------

_D = 16


def _pipe_stage0(p, carry, b):
    x, _y = b
    return jnp.tanh(x @ p["w"] + p["b"])


def _pipe_stage_mid(p, carry, b):
    return jnp.tanh(carry @ p["w"] + p["b"])


def _pipe_stage_last(p, carry, b):
    _x, y = b
    pred = carry @ p["w"] + p["b"]
    return jnp.mean((pred - y) ** 2)


def _pipe_chain(n_stages=4):
    import horovod_tpu as H

    stages = ([_pipe_stage0]
              + [_pipe_stage_mid] * (n_stages - 2) + [_pipe_stage_last])
    return H.ChainedLoss(stages)


def _pipe_params(key, n_stages=4):
    ks = jax.random.split(key, n_stages)
    return [{"w": jax.random.normal(k, (_D, _D)) * _D ** -0.5,
             "b": jnp.zeros((_D,))} for k in ks]


def _pipe_batch(hvd, key, m=4, per_mb=2):
    from horovod_tpu.parallel.training import shard_batch

    n = hvd.size()
    B = n * m * per_mb
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (B, _D))
    y = jax.random.normal(ky, (B, _D))
    return shard_batch((x, y)), (x, y), B


def _leaves_equal(a, b):
    fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    return all(np.asarray(u).tobytes() == np.asarray(v).tobytes()
               for u, v in zip(fa, fb))


def _run_steps(step, params, opt, batch, steps=2):
    p, s = params, opt.init(params)
    loss = None
    for _ in range(steps):
        p, s, loss = step(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    return p, float(loss)


def test_pipeline_step_1f1b_bitwise_equals_gpipe_leg(hvd):
    """The tentpole bitwise gate: same per-stage executables, same
    microbatch accumulation order — the 1F1B interleaving (with
    streamed partial-cycle reduction) reproduces the GPipe-ordered
    dispatch (reduction serialized after a flush fence) bit for bit,
    loss included."""
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch, _, _ = _pipe_batch(hvd, jax.random.PRNGKey(1))
    opt = optax.adam(1e-3)
    kw = dict(num_microbatches=4, fusion_threshold=_D * _D * 4)
    step_f = make_pipeline_train_step(chain, opt, schedule="1f1b", **kw)
    step_g = make_pipeline_train_step(chain, opt, schedule="gpipe", **kw)
    p_f, l_f = _run_steps(step_f, params, opt, batch, 3)
    p_g, l_g = _run_steps(step_g, params, opt, batch, 3)
    assert step_f.plan.schedule == "1f1b"
    assert step_f.bucket_count >= 2 * len(params)
    assert l_f == l_g
    assert _leaves_equal(p_f, p_g)


def test_pipeline_step_interleaved_bitwise(hvd):
    """Interleave changes only the dispatch order — results stay
    bitwise (accumulation order per stage is microbatch order under
    every interleave depth)."""
    chain = _pipe_chain(4)
    params = _pipe_params(jax.random.PRNGKey(0), 4)
    batch, _, _ = _pipe_batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    kw = dict(num_microbatches=4, fusion_threshold=_D * _D * 4)
    p_1, _ = _run_steps(make_pipeline_train_step(
        chain, opt, schedule="1f1b", interleave=1, **kw),
        params, opt, batch)
    p_2, _ = _run_steps(make_pipeline_train_step(
        chain, opt, schedule="1f1b", interleave=2, **kw),
        params, opt, batch)
    assert _leaves_equal(p_1, p_2)


def test_pipeline_step_matches_monolithic_reference(hvd):
    """Loss/grad parity with the monolithic evaluation: one SGD step
    through the pipeline equals p0 - lr * grad(mean-over-microbatches
    loss) (allclose — per-stage programs compile with different fusion
    decisions than one whole-graph backward)."""
    m, n = 4, hvd.size()
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch, (x, y), B = _pipe_batch(hvd, jax.random.PRNGKey(1), m=m)
    opt = optax.sgd(0.1)
    step = make_pipeline_train_step(chain, opt, num_microbatches=m,
                                    schedule="1f1b")

    def mb_of(arr, i):
        lb = B // n
        return jnp.concatenate(
            [arr[r * lb:(r + 1) * lb].reshape(
                m, lb // m, _D)[i] for r in range(n)], 0)

    def ref_loss(p):
        tot = 0.0
        for i in range(m):
            tot = tot + chain(p, (mb_of(x, i), mb_of(y, i)))
        return tot / m

    g_ref = jax.grad(ref_loss)(params)
    p1, _, l1 = step(params, opt.init(params), batch)
    np.testing.assert_allclose(float(l1), float(ref_loss(params)),
                               rtol=1e-5)
    for a, p0, g in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(p0) - 0.1 * np.asarray(g),
            rtol=2e-5, atol=2e-6)


def test_pipeline_steady_state_cache_replay(hvd):
    """After warmup every stage's partial cycle replays from the
    response cache: further steps add ZERO negotiation misses."""
    import horovod_tpu.core.state as state_mod

    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch, _, _ = _pipe_batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    step = make_pipeline_train_step(chain, opt, num_microbatches=4,
                                    schedule="1f1b",
                                    fusion_threshold=_D * _D * 4)
    p, s = params, opt.init(params)
    for _ in range(2):
        p, s, _loss = step(p, s, batch)
    st = state_mod.global_state()
    misses0 = st.response_cache.stats.misses
    replayed0 = st.response_cache.stats.replayed_tensors
    p, s, _loss = step(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    assert st.response_cache.stats.misses == misses0
    n_leaves = len(jax.tree_util.tree_leaves(params))
    assert st.response_cache.stats.replayed_tensors - replayed0 \
        == n_leaves


def test_pipeline_telemetry_and_memory(hvd):
    """pipeline.microbatches counts m per step; bubble_seconds records
    the exposed reduction wait; the in-flight activation gauge reports
    the 1F1B bound (below the GPipe peak at m > S)."""
    import horovod_tpu as H

    m = 8
    chain = _pipe_chain(3)
    params = _pipe_params(jax.random.PRNGKey(0), 3)
    batch, _, _ = _pipe_batch(hvd, jax.random.PRNGKey(1), m=m)
    opt = optax.sgd(0.1)
    base = H.metrics().get("pipeline.microbatches", {}).get("value", 0)
    bubbles0 = H.metrics().get(
        "pipeline.bubble_seconds", {}).get("count", 0)
    step_f = make_pipeline_train_step(chain, opt, num_microbatches=m,
                                      schedule="1f1b")
    _run_steps(step_f, params, opt, batch, 1)
    snap = H.metrics()
    assert snap["pipeline.microbatches"]["value"] - base == m
    assert snap["pipeline.bubble_seconds"]["count"] == bubbles0 + 1
    peak_f = snap["pipeline.inflight_activations"]["value"]
    step_g = make_pipeline_train_step(chain, opt, num_microbatches=m,
                                      schedule="gpipe")
    _run_steps(step_g, params, opt, batch, 1)
    peak_g = H.metrics()["pipeline.inflight_activations"]["value"]
    assert peak_f < peak_g, (peak_f, peak_g)
    assert peak_g == (3 - 1) * m


def test_pipeline_batch_validation_names_counts(hvd):
    """A batch whose axis does not divide by num_microbatches fails
    naming the axis size and the nearest valid counts; a microbatch
    that does not shard by the replica count fails naming both."""
    from horovod_tpu.parallel.training import shard_batch

    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    n = hvd.size()
    step = make_pipeline_train_step(chain, opt, num_microbatches=3)
    B = 4 * n  # divisible by n, not by 3 microbatches
    x = jnp.zeros((B, _D))
    with pytest.raises(ValueError) as ei:
        step(params, opt.init(params), shard_batch((x, x)))
    assert f"size {B}" in str(ei.value)
    assert "num_microbatches=3" in str(ei.value)
    assert "nearest valid counts" in str(ei.value)
    # Divisible by m at the global axis but not per replica.
    step2 = make_pipeline_train_step(chain, opt, num_microbatches=n * 2)
    x2 = jnp.zeros((2 * n, _D))
    with pytest.raises(ValueError, match="per-replica batch"):
        step2(params, opt.init(params), shard_batch((x2, x2)))


def test_pipeline_single_stage_rejected(hvd):
    with pytest.raises(ValueError, match="at least 2 stages"):
        make_pipeline_train_step([_pipe_stage_last], optax.sgd(0.1),
                                 num_microbatches=2)


# ---------------------------------------------------------------------------
# Sub-mesh placement (mp × pipeline; hvd-fuse)
# ---------------------------------------------------------------------------

def _run_steps_placed(step, params, opt, batch, steps=2):
    p, s = params, [opt.init(pp) for pp in params]
    loss = None
    for _ in range(steps):
        p, s, loss = step(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    return p, float(loss)


def _placed_batch(n_rep, m=4, per_mb=2, seed=1):
    B = n_rep * m * per_mb
    kx, ky = jax.random.split(jax.random.PRNGKey(seed))
    return (jax.random.normal(kx, (B, _D)),
            jax.random.normal(ky, (B, _D)))


def test_stage_submeshes_split(hvd):
    meshes = PL.stage_submeshes(4)
    assert len(meshes) == 4
    devs = [tuple(mk.devices.flat) for mk in meshes]
    assert sum(len(d) for d in devs) == len(jax.devices())
    assert len({d for block in devs for d in block}) == len(jax.devices())
    mp = PL.stage_submeshes(2, model=2)
    assert mp[0].shape["hvd"] == 2 and mp[0].shape["model"] == 2
    with pytest.raises(ValueError, match="do not split"):
        PL.stage_submeshes(3)
    with pytest.raises(ValueError, match="not divisible by"):
        PL.stage_submeshes(4, model=3)


def test_pipeline_placed_1f1b_bitwise_equals_gpipe(hvd):
    """The placement bitwise gate: per-stage executables on their own
    sub-meshes, gradients through per-stage fused reduce+apply
    programs — 1F1B (applies streamed at each stage's last backward)
    reproduces the GPipe-ordered dispatch bit for bit."""
    meshes = PL.stage_submeshes(4)
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch = _placed_batch(2)
    opt = optax.adam(1e-3)
    kw = dict(num_microbatches=4, stage_meshes=meshes)
    step_f = make_pipeline_train_step(chain, opt, schedule="1f1b", **kw)
    step_g = make_pipeline_train_step(chain, opt, schedule="gpipe", **kw)
    p_f, l_f = _run_steps_placed(step_f, params, opt, batch, 3)
    p_g, l_g = _run_steps_placed(step_g, params, opt, batch, 3)
    assert step_f.placed and step_f.stage_meshes == meshes
    assert l_f == l_g
    assert _leaves_equal(p_f, p_g)


def test_pipeline_placed_executables_live_on_declared_submeshes(hvd):
    """Real MPMD placement: stage k's updated parameters come back
    committed to exactly stage k's sub-mesh devices."""
    meshes = PL.stage_submeshes(4)
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch = _placed_batch(2)
    opt = optax.sgd(0.1)
    step = make_pipeline_train_step(chain, opt, num_microbatches=4,
                                    stage_meshes=meshes)
    p1, _ = _run_steps_placed(step, params, opt, batch, 1)
    for k, stage_params in enumerate(p1):
        want = set(meshes[k].devices.flat)
        for leaf in jax.tree_util.tree_leaves(stage_params):
            assert set(leaf.sharding.device_set) == want, k


def test_pipeline_placed_matches_unplaced_allclose(hvd):
    """Placed and unplaced steps compute the same mean-loss SGD update
    (allclose, not bitwise: the reduction arithmetic moves from the
    dynamic bucket path over 8 replicas to an in-program psum over
    each stage's 2)."""
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    batch = _placed_batch(8, per_mb=1)  # divides for both layouts
    opt = optax.sgd(0.1)
    step_u = make_pipeline_train_step(chain, opt, num_microbatches=4)
    step_p = make_pipeline_train_step(chain, opt, num_microbatches=4,
                                      stage_meshes=PL.stage_submeshes(4))
    p_u, l_u = _run_steps(step_u, params, opt, batch, 1)
    p_p, l_p = _run_steps_placed(step_p, params, opt, batch, 1)
    np.testing.assert_allclose(l_p, l_u, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_p),
                    jax.tree_util.tree_leaves(p_u)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def _mp_stage0(p, carry, b):
    from horovod_tpu.parallel.tensor import local_shard, tp_mlp

    x, _y = b
    return tp_mlp(x, local_shard(p["w"], 1), None,
                  local_shard(p["w2"], 0), None)


def _mp_stage_last(p, carry, b):
    _x, y = b
    pred = carry @ p["w"] + p["b"]
    return jnp.mean((pred - y) ** 2)


def test_pipeline_placed_mp_composition_bitwise(hvd):
    """mp × pipeline: each stage's sub-mesh carries a model axis and
    the stage body runs the fused tensor-parallel closers inside it —
    1f1b ≡ gpipe stays bitwise under the composition."""
    import horovod_tpu as H

    meshes = PL.stage_submeshes(2, model=2)
    chain = H.ChainedLoss([_mp_stage0, _mp_stage_last])
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    params = [
        {"w": jax.random.normal(k1, (_D, _D)) * _D ** -0.5,
         "w2": jax.random.normal(k2, (_D, _D)) * _D ** -0.5},
        {"w": jax.random.normal(k3, (_D, _D)) * _D ** -0.5,
         "b": jnp.zeros((_D,))},
    ]
    batch = _placed_batch(2)
    opt = optax.sgd(0.1)
    kw = dict(num_microbatches=4, stage_meshes=meshes)
    step_f = make_pipeline_train_step(chain, opt, schedule="1f1b", **kw)
    step_g = make_pipeline_train_step(chain, opt, schedule="gpipe", **kw)
    p_f, l_f = _run_steps_placed(step_f, params, opt, batch, 2)
    p_g, l_g = _run_steps_placed(step_g, params, opt, batch, 2)
    assert np.isfinite(l_f)
    assert l_f == l_g
    assert _leaves_equal(p_f, p_g)


def test_pipeline_placed_validation(hvd):
    chain = _pipe_chain()
    params = _pipe_params(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    with pytest.raises(ValueError, match="one sub-mesh per stage"):
        make_pipeline_train_step(chain, opt, num_microbatches=4,
                                 stage_meshes=PL.stage_submeshes(2))
    bad = make_mesh(pipe=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="replica"):
        make_pipeline_train_step(chain, opt, num_microbatches=4,
                                 stage_meshes=[bad] * 4)
    step = make_pipeline_train_step(chain, opt, num_microbatches=4,
                                    stage_meshes=PL.stage_submeshes(4))
    batch = _placed_batch(2)
    with pytest.raises(ValueError, match="PER-STAGE opt_state"):
        step(params, opt.init(params), batch)
