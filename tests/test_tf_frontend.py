"""TensorFlow frontend tests (≙ reference test/test_tensorflow.py,
re-targeted at TF2 eager)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.frontends.tensorflow as hvdtf  # noqa: E402


def test_allreduce_dense(hvd):
    x = tf.constant([1.0, 2.0, 3.0])
    out = hvdtf.allreduce(x, average=True)
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0, 3.0], rtol=1e-6)
    out = hvdtf.allreduce(x, average=False)
    np.testing.assert_allclose(out.numpy(),
                               np.asarray(x) * hvdtf.size(), rtol=1e-6)


def test_allreduce_indexed_slices(hvd):
    sl = tf.IndexedSlices(values=tf.constant([[1.0, 2.0], [3.0, 4.0]]),
                          indices=tf.constant([1, 5], dtype="int64"),
                          dense_shape=tf.constant([8, 2], dtype="int64"))
    out = hvdtf.allreduce(sl, average=True)
    assert isinstance(out, tf.IndexedSlices)
    # The gather multiplies the row count by size(); densifying the
    # averaged duplicates recovers the original represented tensor
    # (reference semantics, tensorflow/__init__.py:67-78).
    assert out.values.shape[0] == 2 * hvd.size()
    dense = np.zeros((8, 2), "float32")
    np.add.at(dense, out.indices.numpy(), out.values.numpy())
    want = np.zeros((8, 2), "float32")
    want[1] = [1.0, 2.0]
    want[5] = [3.0, 4.0]
    np.testing.assert_allclose(dense, want, rtol=1e-5)


def test_allgather_and_broadcast(hvd):
    x = tf.constant([[1.0, 2.0]])
    out = hvdtf.allgather(x)
    assert out.shape == (hvd.size(), 2)
    out = hvdtf.broadcast(tf.constant([7.0]), root_rank=0)
    np.testing.assert_allclose(out.numpy(), [7.0], rtol=1e-6)


def test_broadcast_variables(hvd):
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0]])
    before = [v1.numpy().copy(), v2.numpy().copy()]
    hvdtf.broadcast_variables([v1, v2], root_rank=0)
    np.testing.assert_allclose(v1.numpy(), before[0], rtol=1e-6)
    np.testing.assert_allclose(v2.numpy(), before[1], rtol=1e-6)


def test_distributed_gradient_tape(hvd):
    w = tf.Variable([2.0])
    with hvdtf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = w * w
    (g,) = tape.gradient(loss, [w])
    np.testing.assert_allclose(np.asarray(g), [4.0], rtol=1e-6)


def test_distributed_optimizer_applies_reduced_grads(hvd):
    opt = hvdtf.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=1.0))
    assert opt.__class__.__name__ == "SGD"
    v = tf.Variable([0.0, 0.0])
    opt.apply_gradients([(tf.constant([1.0, 2.0]), v)])
    np.testing.assert_allclose(v.numpy(), [-1.0, -2.0], rtol=1e-6)


def test_collectives_inside_tf_function(hvd):
    """Round 4: collectives work INSIDE tf.function via the py_function
    bridge (≙ the reference's AsyncOpKernel enqueue from graph
    execution, mpi_ops.cc:270-298).  Repeated executions of the same
    compiled function reuse the trace-time collective name."""
    @tf.function
    def f(x):
        return (hvdtf.allreduce(x, average=False),
                hvdtf.allgather(x),
                hvdtf.broadcast(x, root_rank=0))

    for _ in range(3):  # name reuse across executions
        red, gat, bc = f(tf.constant([1.0, 2.0]))
        np.testing.assert_allclose(red.numpy(),
                                   np.array([1.0, 2.0]) * hvd.size())
        assert gat.shape == (2 * hvd.size(),)
        np.testing.assert_allclose(bc.numpy(), [1.0, 2.0])


def test_indexed_slices_inside_tf_function(hvd):
    @tf.function
    def f(values, indices):
        sl = tf.IndexedSlices(values=values, indices=indices)
        out = hvdtf.allreduce(sl, average=False)
        return out.values, out.indices

    vals, idxs = f(tf.constant([[1.0, 2.0]]),
                   tf.constant([3], dtype="int64"))
    assert vals.shape[0] == hvd.size()
    assert idxs.dtype == tf.int64
    np.testing.assert_allclose(vals.numpy()[0], [1.0, 2.0])


def test_compiled_train_step_through_frontend(hvd):
    """The round-4 verdict's done-condition: a small tf.function-compiled
    train step whose gradients reduce through the frontend mid-graph —
    loss must decrease (graph-mode DistributedGradientTape ≙ the
    reference's session.run(train_op) flow)."""
    w = tf.Variable([0.0, 0.0])
    x = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    y = tf.constant([5.0, 6.0])

    @tf.function
    def train_step():
        with hvdtf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_mean(
                (tf.linalg.matvec(x, w) - y) ** 2)
        (g,) = tape.gradient(loss, [w])
        # Pure-TF SGD update (under KERAS_BACKEND=jax, tf.keras
        # optimizers are Keras-3/JAX objects that cannot consume
        # symbolic tf tensors — the graph-mode update is TF's own).
        w.assign_sub(0.05 * g)
        return loss

    losses = [float(train_step()) for _ in range(20)]
    assert losses[-1] < 0.2 * losses[0], losses


def test_dtype_preserved_float64_int64(hvd):
    x = tf.constant([1.0, 2.0], dtype=tf.float64)
    out = hvdtf.allreduce(x, average=True)
    assert out.dtype == tf.float64
    i = tf.constant([1, 2], dtype=tf.int64)
    out = hvdtf.allgather(i)
    assert out.dtype == tf.int64


def test_indexed_slices_without_dense_shape(hvd):
    sl = tf.IndexedSlices(values=tf.constant([[1.0]]),
                          indices=tf.constant([0], dtype="int64"))
    out = hvdtf.allreduce(sl, average=False)
    assert isinstance(out, tf.IndexedSlices)
    assert out.dense_shape is None
    assert out.indices.dtype == tf.int64


def test_tf_allreduce_op_and_process_set(hvd):
    """The post-v0.13 op= and process_set= kwargs work on the TF
    surface (review finding: the constants were exported but no TF
    collective accepted them)."""
    tf = pytest.importorskip("tensorflow")
    import horovod_tpu.frontends.tensorflow as hvdtf

    t = tf.constant([3.0, -1.0])
    np.testing.assert_allclose(
        hvdtf.allreduce(t, op=hvdtf.Min).numpy(), [3.0, -1.0])
    np.testing.assert_allclose(
        hvdtf.allreduce(tf.constant([2.0]), op=hvdtf.Product).numpy(),
        [2.0 ** hvd.size()])
    ps = hvdtf.add_process_set([0, 1])
    np.testing.assert_allclose(
        hvdtf.allreduce(tf.constant([2.0]), average=False,
                        process_set=ps).numpy(), [4.0])
    with pytest.raises(ValueError, match="not both"):
        hvdtf.allreduce(t, average=True, op=hvdtf.Sum)

    @tf.function
    def f(x):
        return hvdtf.allreduce(x, op=hvdtf.Max, name="tf.fn.max")

    np.testing.assert_allclose(f(tf.constant([5.0])).numpy(), [5.0])


def test_tf_function_gradients_fuse_into_one_wire_collective(hvd):
    """Tensor Fusion must survive graph mode (round-4 verdict item 4):
    the whole DistributedGradientTape batch bridges through ONE
    py_function node whose eager body submits every allreduce async
    before synchronizing, so the coordinator packs all N gradients into
    one flat-buffer wire collective.  Counted at the wire boundary
    (_execute_response), with the background tick paused so fusion is
    deterministic."""
    from horovod_tpu.core import state as _state
    from horovod_tpu.ops import collective as C

    _state.global_state().bg_stop.set()  # inline drain fuses the queue
    responses = []
    real = C._execute_response

    def counting(resp, ops):
        responses.append(sorted(o.name for o in ops))
        return real(resp, ops)

    C._execute_response = counting
    try:
        n_params = 10
        ws = [tf.Variable([float(i + 1), 2.0]) for i in range(n_params)]

        @tf.function
        def step():
            with hvdtf.DistributedGradientTape(tf.GradientTape()) as tape:
                loss = tf.add_n([tf.reduce_sum(w * w) for w in ws])
            return tape.gradient(loss, ws)

        grads = step()
    finally:
        C._execute_response = real
    for w, g in zip(ws, grads):
        np.testing.assert_allclose(g.numpy(), 2.0 * w.numpy(), rtol=1e-6)
    # All N gradients crossed the wire in ONE fused collective.
    fused = [r for r in responses if len(r) > 1]
    assert len(fused) == 1, responses
    assert len(fused[0]) == n_params, responses


def test_tf_grouped_allreduce_eager_and_graph(hvd):
    """grouped_allreduce (torch-frontend parity): correct values in
    eager mode, and inside tf.function the group is ONE py_function
    node / one fused wire collective."""
    from horovod_tpu.core import state as _state
    from horovod_tpu.ops import collective as C

    xs = [tf.constant([float(i + 1)] * 3) for i in range(5)]
    outs = hvdtf.grouped_allreduce(xs, average=False, name="tf.grp")
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            o.numpy(), (i + 1.0) * hvd.size(), rtol=1e-6)

    _state.global_state().bg_stop.set()  # deterministic fusion
    responses = []
    real = C._execute_response

    def counting(resp, ops):
        responses.append([o.name for o in ops])
        return real(resp, ops)

    C._execute_response = counting
    try:
        @tf.function
        def f(*ts):
            return hvdtf.grouped_allreduce(list(ts), average=True,
                                           name="tf.grp.fn")

        outs = f(*xs)
    finally:
        C._execute_response = real
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o.numpy(), i + 1.0, rtol=1e-6)
    fused = [r for r in responses if len(r) > 1]
    assert len(fused) == 1 and len(fused[0]) == 5, responses
