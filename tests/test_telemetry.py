"""hvd-telemetry unit tests (docs/metrics.md).

Covers the registry semantics (log2 histogram buckets, exact totals
under concurrent writers, the disabled-path no-op), the cluster
aggregation math, the Prometheus/JSON exporter endpoint contract, and
the flight recorder — including dumps produced by a SEEDED stall and a
SEEDED cross-rank mismatch through the real coordinator paths.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from horovod_tpu import telemetry
from horovod_tpu.telemetry import exporter as tel_exporter
from horovod_tpu.telemetry import flight as tel_flight
from horovod_tpu.telemetry.registry import (MetricsRegistry, aggregate,
                                            bucket_edges)


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_basics():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c", "help text")
    c.inc()
    c.inc(41)
    g = reg.gauge("g")
    g.set(2.5)
    snap = reg.snapshot()
    assert snap["c"] == {"type": "counter", "value": 42}
    assert snap["g"] == {"type": "gauge", "value": 2.5}
    # get-or-create returns the same object; a kind clash raises.
    assert reg.counter("c") is c
    with pytest.raises(TypeError):
        reg.gauge("c")
    with pytest.raises(TypeError):
        reg.histogram("g", "seconds")


def test_histogram_log2_buckets():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("h", "count")
    edges = bucket_edges("count")
    assert edges[0] == 1.0 and edges[-1] == 4096.0
    # Value -> smallest power-of-two edge covering it.
    for v, expect_le in ((1, 1.0), (2, 2.0), (3, 4.0), (4, 4.0),
                         (5, 8.0), (4096, 4096.0), (0, 1.0)):
        h.observe(v)
        snap = h.snapshot()
        counts = dict((le, n) for le, n in snap["buckets"])
        assert counts[expect_le] >= 1, (v, expect_le, snap)
    snap = h.snapshot()
    assert snap["count"] == 7
    assert snap["overflow"] == 0
    h.observe(5000)  # past the last edge
    assert h.snapshot()["overflow"] == 1


def test_histogram_seconds_microsecond_floor():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("lat", "seconds")
    h.observe(1e-9)   # below the smallest edge: clamps into bucket 0
    h.observe(0.5)
    h.observe(100.0)  # past 32 s: overflow
    snap = h.snapshot()
    assert snap["count"] == 3
    assert snap["buckets"][0][1] == 1
    assert snap["overflow"] == 1
    assert snap["sum"] == pytest.approx(100.5, rel=1e-6)


def test_concurrent_writers_are_exact():
    """The striped per-thread cells make totals EXACT under concurrent
    writers — no lost increments, no torn histogram rows."""
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c")
    h = reg.histogram("h", "count")
    threads_n, per_thread = 8, 20_000

    def work():
        for i in range(per_thread):
            c.inc()
            h.observe((i % 7) + 1)

    threads = [threading.Thread(target=work) for _ in range(threads_n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["c"]["value"] == threads_n * per_thread
    assert snap["h"]["count"] == threads_n * per_thread
    assert sum(n for _le, n in snap["h"]["buckets"]) == \
        threads_n * per_thread


def test_disabled_registry_is_a_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c")
    h = reg.histogram("h", "seconds")
    g = reg.gauge("g")
    c.inc(5)
    h.observe(1.0)
    g.set(3)
    snap = reg.snapshot()
    assert snap["c"]["value"] == 0
    assert snap["h"]["count"] == 0
    assert snap["g"]["value"] == 0
    # Runtime re-enable works (the bench A/B path).
    reg.set_enabled(True)
    c.inc(5)
    assert reg.snapshot()["c"]["value"] == 5


def test_set_enabled_master_switch_silences_flight(monkeypatch):
    was = telemetry.enabled()
    try:
        telemetry.set_enabled(False)
        n0 = len(tel_flight.snapshot())
        tel_flight.record("should_not_appear")
        assert len(tel_flight.snapshot()) == n0
        telemetry.set_enabled(True)
        tel_flight.record("appears")
        assert tel_flight.snapshot()[-1][1] == "appears"
    finally:
        telemetry.set_enabled(was)


def test_collectors_run_at_snapshot_and_never_break_it():
    reg = MetricsRegistry(enabled=True)
    calls = []

    def ok(r):
        calls.append(1)
        r.gauge("pull.g").set(7)

    def broken(r):
        raise RuntimeError("collector bug")

    reg.register_collector("ok", ok)
    reg.register_collector("broken", broken)
    snap = reg.snapshot()
    assert calls and snap["pull.g"]["value"] == 7
    reg.unregister_collector("ok")
    reg.snapshot()
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------

def _mk_snapshot(counter_v, hist_values):
    reg = MetricsRegistry(enabled=True)
    reg.counter("c").inc(counter_v)
    h = reg.histogram("h", "count")
    for v in hist_values:
        h.observe(v)
    return reg.snapshot()

def test_aggregate_scalars_and_histograms():
    snaps = {0: _mk_snapshot(10, [1, 2, 4]),
             1: _mk_snapshot(30, [8, 16, 32])}
    agg = aggregate(snaps)
    c = agg["c"]
    assert c["ranks"] == 2 and c["min"] == 10 and c["max"] == 30
    assert c["mean"] == 20 and c["sum"] == 40
    assert c["per_rank"] == {0: 10, 1: 30}
    h = agg["h"]
    assert h["ranks"] == 2 and h["count"] == 6
    assert h["mean"] == pytest.approx(63 / 6)
    assert h["p50"] == 4.0       # 3rd of 6 observations
    assert h["p99"] == 32.0
    # A metric present on one rank only still aggregates.
    snaps[1]["only1"] = {"type": "gauge", "value": 5}
    agg = aggregate(snaps)
    assert agg["only1"]["ranks"] == 1 and agg["only1"]["mean"] == 5


# ---------------------------------------------------------------------------
# Exporter endpoint contract
# ---------------------------------------------------------------------------

def test_exporter_endpoints():
    reg = MetricsRegistry(enabled=True)
    reg.counter("exp.count").inc(3)
    reg.histogram("exp.lat", "seconds").observe(0.25)
    exp = tel_exporter.start_exporter(reg, 0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{exp.port}"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=5).read().decode()
        assert "# TYPE hvd_exp_count counter" in text
        assert "hvd_exp_count 3" in text
        assert 'hvd_exp_lat_bucket{le="+Inf"} 1' in text
        assert "hvd_exp_lat_count 1" in text

        js = json.loads(urllib.request.urlopen(
            f"{base}/metrics?format=json", timeout=5).read())
        assert js["exp.count"]["value"] == 3

        health = urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5)
        assert health.status == 200
        assert json.loads(health.read())["status"] == "ok"

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        exp.close()


def test_exporter_started_by_init_on_env_port(monkeypatch):
    import jax

    import horovod_tpu as hvd

    monkeypatch.setenv("HVD_TPU_METRICS_PORT", "0")
    hvd.init(devices=jax.devices())
    try:
        from horovod_tpu.core import state as state_mod

        exp = state_mod.global_state().metrics_exporter
        assert exp is not None
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/healthz", timeout=5).read())
        assert health["initialized"] is True and health["rank"] == 0
    finally:
        hvd.shutdown()
    assert state_mod.global_state().metrics_exporter is None


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded():
    rec = tel_flight.FlightRecorder(capacity=100, enabled=True)
    for i in range(250):
        rec.record("tick", i)
    events = rec.snapshot()
    assert len(events) == 100
    assert events[0][2] == (150,) and events[-1][2] == (249,)


def test_flight_dump_format_and_rate_limit(tmp_path):
    rec = tel_flight.FlightRecorder(capacity=10, enabled=True)
    rec.record("submit", "grad.0", 0)
    rec.record("stall", "Tensor grad.0 pending")
    path = rec.dump("unit-test", extra={"k": "v"},
                    directory=str(tmp_path))
    assert path is not None
    payload = json.loads(open(path).read())
    assert payload["format"] == "hvd-flight-v1"
    assert payload["reason"] == "unit-test"
    assert payload["extra"] == {"k": "v"}
    assert payload["events"][-1]["kind"] == "stall"
    # Same reason inside the rate window: suppressed.
    assert rec.dump("unit-test", directory=str(tmp_path)) is None
    # Different reason: allowed.
    assert rec.dump("other", directory=str(tmp_path)) is not None
    # No directory configured: no-op, never raises.
    assert rec.dump("unit-test") is None or tel_flight.flight_dir()


def test_flight_dump_on_seeded_stall(monkeypatch, tmp_path):
    """A stall through the REAL coordinator facade produces a flight
    dump whose tail names the stalled tensor and the non-ready ranks
    (the acceptance contract of ISSUE 4)."""
    from horovod_tpu.ops import coordinator as coord_mod
    from horovod_tpu.ops import wire

    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setattr(coord_mod, "STALL_WARNING_SECONDS", -1.0)
    tel_flight.recorder._last_dump.pop("stall", None)
    stalls0 = telemetry.registry().snapshot(
        run_collectors=False)["events.stall_warnings"]["value"]

    coord = coord_mod.Coordinator(size=2, fusion_threshold=1 << 20)
    coord.submit(wire.Request(
        request_rank=0, request_type=wire.RequestType.ALLREDUCE,
        tensor_type=wire.DataType.FLOAT32, tensor_name="stalled.op",
        tensor_shape=(4,)))
    resps = coord.poll_responses({})  # rank 1 never submitted
    assert resps == []
    coord.close()

    snap = telemetry.registry().snapshot(run_collectors=False)
    assert snap["events.stall_warnings"]["value"] > stalls0
    files = sorted(tmp_path.glob("hvd_flight_*stall*.json"))
    assert files, list(tmp_path.iterdir())
    payload = json.loads(files[-1].read_text())
    stall_events = [e for e in payload["events"] if e["kind"] == "stall"]
    assert stall_events, payload["events"][-5:]
    tail = stall_events[-1]["args"][0]
    assert "stalled.op" in tail, tail
    assert "waiting on replicas: [1]" in tail, tail
    # The ring also shows the submit that started the stalled op.
    assert any(e["kind"] == "submit" and "stalled.op" in e["args"]
               for e in payload["events"])
    assert payload["extra"]["warnings"]


def test_flight_dump_on_seeded_mismatch(hvd, monkeypatch, tmp_path):
    """A cross-rank shape mismatch through the real validation path
    dumps the ring with the full diagnostic."""
    from horovod_tpu.ops import collective as C
    from horovod_tpu.ops import wire
    from horovod_tpu.ops.coordinator import PyCoordinator

    monkeypatch.setenv("HVD_TPU_FLIGHT_DIR", str(tmp_path))
    tel_flight.recorder._last_dump.pop("error", None)

    coord = PyCoordinator(size=2, fusion_threshold=1 << 20)
    for rank, shape in ((0, (2,)), (1, (3,))):
        coord.submit(wire.Request(
            request_rank=rank, request_type=wire.RequestType.ALLREDUCE,
            tensor_type=wire.DataType.FLOAT32, tensor_name="bad.shape",
            tensor_shape=shape))
    errs = [r for r in coord.poll_responses({})
            if r.response_type == wire.ResponseType.ERROR]
    assert errs and "Mismatched allreduce tensor shapes" in \
        errs[0].error_message
    C._execute_response(errs[0], [])

    files = sorted(tmp_path.glob("hvd_flight_*error*.json"))
    assert files, list(tmp_path.iterdir())
    payload = json.loads(files[-1].read_text())
    assert "Mismatched allreduce tensor shapes" in \
        payload["extra"]["message"]


# ---------------------------------------------------------------------------
# End-to-end local metrics + single-process cluster aggregation
# ---------------------------------------------------------------------------

def test_metrics_snapshot_after_collectives(hvd):
    base = hvd.metrics()
    out = hvd.allreduce(np.ones((8,), np.float32), average=False,
                        name="tel.e2e")
    np.testing.assert_allclose(np.asarray(out)[0], hvd.size())
    snap = hvd.metrics()
    assert snap["collective.submitted"]["value"] > \
        base["collective.submitted"]["value"]
    assert snap["collective.completed"]["value"] > \
        base["collective.completed"]["value"]
    assert snap["collective.negotiate_seconds"]["count"] >= 1
    assert snap["collective.payload_bytes"]["count"] >= 1
    assert snap["fusion.group_width"]["count"] >= 1
    # Pull-side gauges from the runtime collector.
    assert "handles.live" in snap
    assert "megakernel.builds" in snap
    assert "cache.hits" in snap  # response cache on by default


def test_cluster_metrics_single_process(hvd):
    hvd.allreduce(np.ones((4,), np.float32), average=False,
                  name="tel.agg")
    agg = hvd.cluster_metrics()
    m = agg["collective.submitted"]
    assert m["ranks"] == 1 and m["sum"] >= 1
    h = agg["collective.negotiate_seconds"]
    assert h["count"] >= 1 and h["p50"] is not None


# ---------------------------------------------------------------------------
# Bounded kernel caches (ISSUE 4 satellite: ops/collective.py)
# ---------------------------------------------------------------------------

def test_kernel_cache_evicts_stale_device_entries(hvd):
    """Entries keyed on Device objects that no longer appear in
    jax.devices() (a restarted backend) are evicted on the next miss
    instead of living forever (the old unbounded lru_cache)."""
    import jax

    from horovod_tpu.ops import collective as C

    fake_key = ("dead-device-0", "dead-device-1")
    fresh_key = tuple(jax.devices()[:3])
    with C._kernel_cache_lock:
        C._kernel_caches["replica"][fake_key] = {"stale": None}
        C._kernel_caches["subset"].pop(fresh_key, None)  # force a miss
    mesh, ks = C._subset_kernels(fresh_key)
    assert "psum_pr" in ks
    with C._kernel_cache_lock:
        assert fake_key not in C._kernel_caches["replica"]
        assert fresh_key in C._kernel_caches["subset"]
    # Live entries survive (same-backend re-inits share compilations).
    assert C._subset_kernels(fresh_key)[1] is ks
