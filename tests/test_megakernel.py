"""Megakernel executor tests (ops/megakernel.py).

Covers the dataplane PR's contracts:
  * numerical identity — megakernel results BITWISE-identical to the
    per-tensor eager path across dtypes, reduce ops, layouts and
    process sets;
  * dispatch-count regression — exactly one XLA executable launch per
    fusion group in the steady state (real launches counted at jax's
    dispatch choke point, utils/xla_dispatch.py);
  * donation safety — executor-owned input buffers are donated and
    never read (or even referenced) after dispatch;
  * hierarchical ICI×DCN allreduce — equivalent to the flat psum on a
    multi-slice dryrun mesh, including the compressed-DCN-leg variant;
  * executable-cache behavior — plan-digest keyed reuse, bounded size,
    the fusion-threshold invalidation hook;
  * the AVERAGE-divide folds on the non-megakernel kernels
    (reducescatter, replicated broadcast).
"""

import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import megakernel as mk
from horovod_tpu.utils import xla_dispatch


@pytest.fixture(autouse=True)
def _restore_megakernel():
    yield
    mk.set_enabled(None)


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape
    assert a.tobytes() == b.tobytes(), "results not bitwise identical"


def _both_paths(run):
    """Run ``run(tag)`` with the eager executor and the megakernel and
    return both result lists."""
    mk.set_enabled(False)
    eager = run("eager")
    mk.set_enabled(True)
    fused = run("mega")
    return eager, fused


# ---------------------------------------------------------------------------
# Numerical identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op_name", ["Average", "Sum", "Min", "Max",
                                     "Product"])
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_identity_fused_per_replica(hvd, op_name, dtype):
    n = hvd.size()
    op = getattr(hvd, op_name)
    rng = np.random.default_rng(42)
    if dtype == np.float32:
        base = [rng.standard_normal((n, 3, 2)).astype(dtype)
                for _ in range(4)]
    else:
        base = [rng.integers(1, 5, size=(n, 3, 2)).astype(dtype)
                for _ in range(4)]
    inputs = [hvd.shard(t) for t in base]

    def run(tag):
        return [np.asarray(o) for o in hvd.grouped_allreduce(
            inputs, op=op, name=f"mkid.{op_name}.{np.dtype(dtype).name}."
                                f"{tag}")]

    eager, fused = _both_paths(run)
    for a, b in zip(eager, fused):
        _bitwise_equal(a, b)


def test_identity_replicated_host_inputs(hvd):
    # Host numpy contributions (executor-owned → donated) in a fused
    # AVERAGE group, mixed shapes including a scalar.
    vals = [np.arange(6.0, dtype=np.float32).reshape(2, 3),
            np.float32(5.0),
            np.arange(4.0, dtype=np.float32)]

    def run(tag):
        return [np.asarray(o) for o in hvd.grouped_allreduce(
            [v.copy() if isinstance(v, np.ndarray) else v for v in vals],
            average=True, name=f"mkrep.{tag}")]

    eager, fused = _both_paths(run)
    for a, b in zip(eager, fused):
        _bitwise_equal(a, b)
    # Replicated average over identical contributions is the identity.
    np.testing.assert_array_equal(fused[0], vals[0])


def test_identity_single_tensor(hvd):
    n = hvd.size()
    pr = hvd.shard(np.arange(n * 4, dtype=np.float32).reshape(n, 4))

    def run(tag):
        return [np.asarray(hvd.allreduce(pr, average=True,
                                         name=f"mksingle.{tag}"))]

    eager, fused = _both_paths(run)
    _bitwise_equal(eager[0], fused[0])
    np.testing.assert_allclose(
        fused[0], np.broadcast_to(
            np.arange(n * 4, dtype=np.float32).reshape(n, 4)
            .mean(axis=0), (n, 4)))


def test_identity_process_set(hvd):
    ps = hvd.add_process_set([0, 2, 5])
    x = np.arange(8.0, dtype=np.float32)

    def run(tag):
        return [np.asarray(hvd.allreduce(
            x, average=False, name=f"mkps.{tag}", process_set=ps))]

    eager, fused = _both_paths(run)
    _bitwise_equal(eager[0], fused[0])
    np.testing.assert_allclose(fused[0], x * 3)
    hvd.remove_process_set(ps)


def test_adasum_still_uses_dedicated_kernels(hvd):
    # Adasum never routes through the megakernel (its dots are
    # per-tensor); the dedicated ladder/VHDD kernels must keep running
    # under the default-on executor.
    n = hvd.size()
    launches0 = mk.stats.launches
    pr = hvd.shard(np.stack([np.full(4, float(i + 1), np.float32)
                             for i in range(n)]))
    out = np.asarray(hvd.allreduce(pr, op=hvd.Adasum, name="mkadasum"))
    assert out.shape == (n, 4)
    assert mk.stats.launches == launches0


# ---------------------------------------------------------------------------
# Dispatch-count regression (one executable launch per fusion group)
# ---------------------------------------------------------------------------

def test_steady_state_one_dispatch_per_group(hvd):
    import horovod_tpu.core.state as state_mod

    n = hvd.size()
    inputs = [hvd.shard(np.full((n, 16), float(j), np.float32))
              for j in range(6)]

    def cycle():
        hs = [hvd.allreduce_async(x, average=True, name=f"mkdisp.{j}")
              for j, x in enumerate(inputs)]
        return [hvd.synchronize(h) for h in hs]

    mk.set_enabled(True)
    cycle()  # cold: compile + populate the response cache
    cycle()  # warm: the steady state (replayed negotiation)
    st = state_mod.global_state()
    replayed0 = st.response_cache.stats.replayed_tensors
    launches0 = mk.stats.launches
    with xla_dispatch.exact_scope():
        with xla_dispatch.record(all_threads=True) as scope:
            cycle()
    groups = mk.stats.launches - launches0
    assert groups >= 1
    # THE contract: the fused path issues exactly one executable launch
    # per fusion group — any eager-op creep (a stray reshape, slice or
    # divide on the drain path) breaks this equality.
    assert scope.count == groups, (
        f"steady-state cycle issued {scope.count} XLA dispatches for "
        f"{groups} fusion group(s); the megakernel contract is exactly "
        f"one per group")
    # And the cycle really was the steady state: negotiation replayed
    # from the response cache, not re-run.
    assert st.response_cache.stats.replayed_tensors > replayed0


def test_no_creep_invariant_suite_wide(hvd):
    # Accumulated across every megakernel launch of the whole test
    # session (conftest arms HVD_TPU_COUNT_DISPATCHES for the suite):
    # a launch can contribute at most one observed dispatch — more
    # means eager ops crept inside the launch window.
    mk.set_enabled(True)
    x = np.ones(4, np.float32)
    hvd.allreduce(x, average=True, name="mkinv")
    assert mk.stats.launches > 0
    assert mk.stats.launch_dispatches <= mk.stats.launches


# ---------------------------------------------------------------------------
# Donation safety
# ---------------------------------------------------------------------------

def test_donated_inputs_dropped_after_dispatch(hvd):
    mk.set_enabled(True)
    donated0 = mk.stats.donated_inputs
    src = np.arange(32.0, dtype=np.float32)
    out = np.asarray(hvd.allreduce(src, average=True, name="mkdonate"))
    np.testing.assert_array_equal(out, src)  # user's numpy untouched
    assert mk.stats.donated_inputs > donated0, \
        "host-converted contribution was not donated"
    # The executor must hold NO reference to the donated buffer after
    # dispatch (use-after-donate on the drain thread would raise on a
    # deleted array; a surviving reference here is the leak that makes
    # it possible).
    probes = list(mk.last_donated)
    assert probes
    gc.collect()
    alive = [r() for r in probes if r() is not None]
    for arr in alive:
        # jax may keep the object alive internally briefly; what must
        # hold is that donation went through — the buffer is deleted,
        # so ANY later read would raise instead of returning stale data.
        assert arr.is_deleted()


def test_user_arrays_never_donated(hvd):
    n = hvd.size()
    x = hvd.shard(np.ones((n, 8), np.float32))  # user-held jax.Array
    hvd.allreduce(x, average=False, name="mkuser.1")
    # The user's array must remain fully usable afterwards.
    assert not x.is_deleted()
    out2 = np.asarray(hvd.allreduce(x, average=False, name="mkuser.2"))
    np.testing.assert_array_equal(out2, np.full((n, 8), float(n)))


# ---------------------------------------------------------------------------
# Hierarchical ICI×DCN allreduce
# ---------------------------------------------------------------------------

def test_hierarchical_matches_flat_psum(hvd, monkeypatch):
    n = hvd.size()
    # Integer-valued floats: exact under any summation order, so flat
    # vs hierarchical compare bitwise, not just allclose.
    base = [np.arange(n * 5, dtype=np.float32).reshape(n, 5) * (j + 1)
            for j in range(3)]
    inputs = [hvd.shard(t) for t in base]

    mk.set_enabled(True)
    flat = [np.asarray(o) for o in hvd.grouped_allreduce(
        inputs, average=True, name="mkhier.flat")]

    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    hier0 = mk.stats.hier_launches
    hier = [np.asarray(o) for o in hvd.grouped_allreduce(
        inputs, average=True, name="mkhier.hier")]
    assert mk.stats.hier_launches > hier0, \
        "hierarchical kernel did not run on the declared 2-slice mesh"
    for a, b in zip(flat, hier):
        _bitwise_equal(a, b)


@pytest.mark.parametrize("slices", [2, 4])
def test_hierarchical_slice_counts(hvd, monkeypatch, slices):
    n = hvd.size()
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", str(slices))
    mk.set_enabled(True)
    # Ragged flat length (13 not divisible by ici_size) exercises the
    # pad/unpad inside the kernel.
    pr = hvd.shard(np.arange(n * 13, dtype=np.float32).reshape(n, 13))
    out = np.asarray(hvd.allreduce(
        pr, average=False, name=f"mkhier.s{slices}"))
    ref = np.broadcast_to(
        np.arange(n * 13, dtype=np.float32).reshape(n, 13).sum(axis=0),
        (n, 13))
    np.testing.assert_array_equal(out, ref)


def test_hierarchical_dcn_compression(hvd, monkeypatch):
    n = hvd.size()
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "bf16")
    mk.set_enabled(True)
    # Small integers: partial sums fit bf16's mantissa exactly, so the
    # compressed DCN leg is still exact here (the general case is
    # lossy by design — that is the bandwidth trade).
    pr = hvd.shard(np.ones((n, 8), np.float32))
    out = np.asarray(hvd.allreduce(pr, average=False, name="mkdcn"))
    np.testing.assert_array_equal(out, np.full((n, 8), float(n)))


def test_hierarchical_off_by_default(hvd):
    hier0 = mk.stats.hier_launches
    mk.set_enabled(True)
    n = hvd.size()
    hvd.allreduce(hvd.shard(np.ones((n, 4), np.float32)),
                  average=False, name="mkflat")
    assert mk.stats.hier_launches == hier0


def test_replica_hierarchy_detection(monkeypatch):
    from horovod_tpu.core import topology

    devs = jax.devices()
    assert topology.replica_hierarchy(devs) is None  # flat CPU mesh
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    h = topology.replica_hierarchy(devs)
    assert h is not None and h.n_slices == 2
    assert h.ici_size == len(devs) // 2
    assert h.ici_groups[0] == tuple(range(h.ici_size))
    assert h.dcn_groups[0] == (0, h.ici_size)
    # Off wins over declared slices.
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "off")
    assert topology.replica_hierarchy(devs) is None
    # Non-tiling virtual slice count degrades to flat.
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "3")
    assert topology.replica_hierarchy(devs) is None
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "bogus")
    with pytest.raises(ValueError):
        topology.replica_hierarchy(devs)


# ---------------------------------------------------------------------------
# Executable cache
# ---------------------------------------------------------------------------

def test_executable_reuse_across_cycles(hvd):
    n = hvd.size()
    inputs = [hvd.shard(np.ones((n, 8), np.float32)) for _ in range(3)]
    mk.set_enabled(True)

    def cycle(i):
        return hvd.grouped_allreduce(inputs, average=True,
                                     name=f"mkreuse.{i}")

    cycle(0)
    builds0, hits0 = mk.stats.builds, mk.stats.cache_hits
    cycle(1)  # same structure, different names → same executable
    assert mk.stats.builds == builds0, \
        "steady-state cycle recompiled its megakernel"
    assert mk.stats.cache_hits > hits0


def test_plan_digest_recorded(hvd):
    n = hvd.size()
    mk.set_enabled(True)
    x = hvd.shard(np.ones((n, 7), np.float32))
    hvd.allreduce(x, average=True, name="mkdigest")
    # The compiled executable is recorded under the PR 2 fusion-plan
    # digest: digest → spec → digest round-trips.
    with mk._lock:
        digests = dict(mk._digests)
    assert digests, "no plan digest recorded for a cold compile"
    for spec, digest in digests.items():
        assert mk.spec_for_digest(digest) == spec


def test_fusion_threshold_flushes_executables(hvd):
    import horovod_tpu.core.state as state_mod

    mk.set_enabled(True)
    x = np.ones(4, np.float32)
    hvd.allreduce(x, average=True, name="mkflush.1")
    assert mk.cache_size() > 0
    flushes0 = mk.stats.flushes
    st = state_mod.global_state()
    st.coordinator.set_fusion_threshold(32 << 20)
    assert mk.cache_size() == 0
    assert mk.stats.flushes > flushes0
    # And the executor rebuilds transparently afterwards.
    out = np.asarray(hvd.allreduce(x, average=True, name="mkflush.2"))
    np.testing.assert_array_equal(out, x)


# ---------------------------------------------------------------------------
# Satellite folds + vectorized ragged allgather
# ---------------------------------------------------------------------------

def test_ragged_allgather_vectorized(hvd):
    n = hvd.size()
    sizes = [3, 0, 2, 1, 4, 2, 1, 3][:n]
    parts = [np.arange(s * 2, dtype=np.float32).reshape(s, 2) + 100 * i
             for i, s in enumerate(sizes)]
    out = np.asarray(hvd.allgather(list(parts), name="mkragged"))
    np.testing.assert_array_equal(out, np.concatenate(parts, axis=0))


def test_ragged_allgather_all_empty(hvd):
    n = hvd.size()
    parts = [np.zeros((0, 3), np.float32) for _ in range(n)]
    out = np.asarray(hvd.allgather(list(parts), name="mkempty"))
    assert out.shape == (0, 3)


def test_reducescatter_average_fold(hvd):
    n = hvd.size()
    x = np.arange(n * 2 * 3, dtype=np.float32).reshape(n * 2, 3)
    out = np.asarray(hvd.reducescatter(x, average=True, name="mkrs.f"))
    ref = np.stack([x[r * 2:(r + 1) * 2] for r in range(n)])
    np.testing.assert_allclose(out, ref)  # mean of n identical copies
    # Integer AVERAGE floor-divides, matching _divide's contract.
    xi = np.full((n, 2), 5, np.int32)
    outi = np.asarray(hvd.reducescatter(xi, op=hvd.Average,
                                        name="mkrs.i"))
    np.testing.assert_array_equal(
        outi, np.full((n, 1, 2), (5 * n) // n, np.int32))


def test_broadcast_replicated_fold(hvd):
    x = np.arange(5.0, dtype=np.float32)
    out = np.asarray(hvd.broadcast(x, 0, name="mkbc.f"))
    np.testing.assert_array_equal(out, x)
    xi = np.arange(5, dtype=np.int32)
    outi = np.asarray(hvd.broadcast(xi, 0, name="mkbc.i"))
    np.testing.assert_array_equal(outi, xi)


def test_eager_fallback_disables_megakernel(hvd):
    mk.set_enabled(False)
    launches0 = mk.stats.launches
    n = hvd.size()
    out = np.asarray(hvd.allreduce(
        hvd.shard(np.ones((n, 4), np.float32)), average=True,
        name="mkoff"))
    np.testing.assert_array_equal(out, np.ones((n, 4), np.float32))
    assert mk.stats.launches == launches0
