"""Megakernel executor tests (ops/megakernel.py).

Covers the dataplane PR's contracts:
  * numerical identity — megakernel results BITWISE-identical to the
    per-tensor eager path across dtypes, reduce ops, layouts and
    process sets;
  * dispatch-count regression — exactly one XLA executable launch per
    fusion group in the steady state (real launches counted at jax's
    dispatch choke point, utils/xla_dispatch.py);
  * donation safety — executor-owned input buffers are donated and
    never read (or even referenced) after dispatch;
  * hierarchical ICI×DCN allreduce — equivalent to the flat psum on a
    multi-slice dryrun mesh, including the compressed-DCN-leg variant;
  * executable-cache behavior — plan-digest keyed reuse, bounded size,
    the fusion-threshold invalidation hook;
  * the AVERAGE-divide folds on the non-megakernel kernels
    (reducescatter, replicated broadcast).
"""

import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import megakernel as mk
from horovod_tpu.utils import xla_dispatch


@pytest.fixture(autouse=True)
def _restore_megakernel():
    yield
    mk.set_enabled(None)


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape
    assert a.tobytes() == b.tobytes(), "results not bitwise identical"


def _both_paths(run):
    """Run ``run(tag)`` with the eager executor and the megakernel and
    return both result lists."""
    mk.set_enabled(False)
    eager = run("eager")
    mk.set_enabled(True)
    fused = run("mega")
    return eager, fused


# ---------------------------------------------------------------------------
# Numerical identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op_name", ["Average", "Sum", "Min", "Max",
                                     "Product"])
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_identity_fused_per_replica(hvd, op_name, dtype):
    n = hvd.size()
    op = getattr(hvd, op_name)
    rng = np.random.default_rng(42)
    if dtype == np.float32:
        base = [rng.standard_normal((n, 3, 2)).astype(dtype)
                for _ in range(4)]
    else:
        base = [rng.integers(1, 5, size=(n, 3, 2)).astype(dtype)
                for _ in range(4)]
    inputs = [hvd.shard(t) for t in base]

    def run(tag):
        return [np.asarray(o) for o in hvd.grouped_allreduce(
            inputs, op=op, name=f"mkid.{op_name}.{np.dtype(dtype).name}."
                                f"{tag}")]

    eager, fused = _both_paths(run)
    for a, b in zip(eager, fused):
        _bitwise_equal(a, b)


def test_identity_replicated_host_inputs(hvd):
    # Host numpy contributions (executor-owned → donated) in a fused
    # AVERAGE group, mixed shapes including a scalar.
    vals = [np.arange(6.0, dtype=np.float32).reshape(2, 3),
            np.float32(5.0),
            np.arange(4.0, dtype=np.float32)]

    def run(tag):
        return [np.asarray(o) for o in hvd.grouped_allreduce(
            [v.copy() if isinstance(v, np.ndarray) else v for v in vals],
            average=True, name=f"mkrep.{tag}")]

    eager, fused = _both_paths(run)
    for a, b in zip(eager, fused):
        _bitwise_equal(a, b)
    # Replicated average over identical contributions is the identity.
    np.testing.assert_array_equal(fused[0], vals[0])


def test_identity_single_tensor(hvd, monkeypatch):
    # Exact-mean assertion: pin the identity compressor (the CI leg
    # re-runs this file under HVD_TPU_COMPRESSION=int8).
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    n = hvd.size()
    pr = hvd.shard(np.arange(n * 4, dtype=np.float32).reshape(n, 4))

    def run(tag):
        return [np.asarray(hvd.allreduce(pr, average=True,
                                         name=f"mksingle.{tag}"))]

    eager, fused = _both_paths(run)
    _bitwise_equal(eager[0], fused[0])
    np.testing.assert_allclose(
        fused[0], np.broadcast_to(
            np.arange(n * 4, dtype=np.float32).reshape(n, 4)
            .mean(axis=0), (n, 4)))


def test_identity_process_set(hvd):
    ps = hvd.add_process_set([0, 2, 5])
    x = np.arange(8.0, dtype=np.float32)

    def run(tag):
        return [np.asarray(hvd.allreduce(
            x, average=False, name=f"mkps.{tag}", process_set=ps))]

    eager, fused = _both_paths(run)
    _bitwise_equal(eager[0], fused[0])
    np.testing.assert_allclose(fused[0], x * 3)
    hvd.remove_process_set(ps)


def test_adasum_still_uses_dedicated_kernels(hvd):
    # Adasum never routes through the megakernel (its dots are
    # per-tensor); the dedicated ladder/VHDD kernels must keep running
    # under the default-on executor.
    n = hvd.size()
    launches0 = mk.stats.launches
    pr = hvd.shard(np.stack([np.full(4, float(i + 1), np.float32)
                             for i in range(n)]))
    out = np.asarray(hvd.allreduce(pr, op=hvd.Adasum, name="mkadasum"))
    assert out.shape == (n, 4)
    assert mk.stats.launches == launches0


# ---------------------------------------------------------------------------
# Dispatch-count regression (one executable launch per fusion group)
# ---------------------------------------------------------------------------

def test_steady_state_one_dispatch_per_group(hvd):
    import horovod_tpu.core.state as state_mod

    n = hvd.size()
    inputs = [hvd.shard(np.full((n, 16), float(j), np.float32))
              for j in range(6)]

    def cycle():
        hs = [hvd.allreduce_async(x, average=True, name=f"mkdisp.{j}")
              for j, x in enumerate(inputs)]
        return [hvd.synchronize(h) for h in hs]

    mk.set_enabled(True)
    cycle()  # cold: compile + populate the response cache
    cycle()  # warm: the steady state (replayed negotiation)
    st = state_mod.global_state()
    replayed0 = st.response_cache.stats.replayed_tensors
    launches0 = mk.stats.launches
    with xla_dispatch.exact_scope():
        with xla_dispatch.record(all_threads=True) as scope:
            cycle()
    groups = mk.stats.launches - launches0
    assert groups >= 1
    # THE contract: the fused path issues exactly one executable launch
    # per fusion group — any eager-op creep (a stray reshape, slice or
    # divide on the drain path) breaks this equality.
    assert scope.count == groups, (
        f"steady-state cycle issued {scope.count} XLA dispatches for "
        f"{groups} fusion group(s); the megakernel contract is exactly "
        f"one per group")
    # And the cycle really was the steady state: negotiation replayed
    # from the response cache, not re-run.
    assert st.response_cache.stats.replayed_tensors > replayed0


def test_no_creep_invariant_suite_wide(hvd):
    # Accumulated across every megakernel launch of the whole test
    # session (conftest arms HVD_TPU_COUNT_DISPATCHES for the suite):
    # a launch can contribute at most one observed dispatch — more
    # means eager ops crept inside the launch window.
    mk.set_enabled(True)
    x = np.ones(4, np.float32)
    hvd.allreduce(x, average=True, name="mkinv")
    assert mk.stats.launches > 0
    assert mk.stats.launch_dispatches <= mk.stats.launches


# ---------------------------------------------------------------------------
# Donation safety
# ---------------------------------------------------------------------------

def test_donated_inputs_dropped_after_dispatch(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    mk.set_enabled(True)
    donated0 = mk.stats.donated_inputs
    src = np.arange(32.0, dtype=np.float32)
    out = np.asarray(hvd.allreduce(src, average=True, name="mkdonate"))
    np.testing.assert_array_equal(out, src)  # user's numpy untouched
    assert mk.stats.donated_inputs > donated0, \
        "host-converted contribution was not donated"
    # The executor must hold NO reference to the donated buffer after
    # dispatch (use-after-donate on the drain thread would raise on a
    # deleted array; a surviving reference here is the leak that makes
    # it possible).
    probes = list(mk.last_donated)
    assert probes
    gc.collect()
    alive = [r() for r in probes if r() is not None]
    for arr in alive:
        # jax may keep the object alive internally briefly; what must
        # hold is that donation went through — the buffer is deleted,
        # so ANY later read would raise instead of returning stale data.
        assert arr.is_deleted()


def test_user_arrays_never_donated(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    n = hvd.size()
    x = hvd.shard(np.ones((n, 8), np.float32))  # user-held jax.Array
    hvd.allreduce(x, average=False, name="mkuser.1")
    # The user's array must remain fully usable afterwards.
    assert not x.is_deleted()
    out2 = np.asarray(hvd.allreduce(x, average=False, name="mkuser.2"))
    np.testing.assert_array_equal(out2, np.full((n, 8), float(n)))


# ---------------------------------------------------------------------------
# Hierarchical ICI×DCN allreduce
# ---------------------------------------------------------------------------

def test_hierarchical_matches_flat_psum(hvd, monkeypatch):
    # Flat vs hierarchical are bitwise-equal only uncompressed (the
    # quantized pipelines use different exchange topologies).
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    n = hvd.size()
    # Integer-valued floats: exact under any summation order, so flat
    # vs hierarchical compare bitwise, not just allclose.
    base = [np.arange(n * 5, dtype=np.float32).reshape(n, 5) * (j + 1)
            for j in range(3)]
    inputs = [hvd.shard(t) for t in base]

    mk.set_enabled(True)
    flat = [np.asarray(o) for o in hvd.grouped_allreduce(
        inputs, average=True, name="mkhier.flat")]

    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    hier0 = mk.stats.hier_launches
    hier = [np.asarray(o) for o in hvd.grouped_allreduce(
        inputs, average=True, name="mkhier.hier")]
    assert mk.stats.hier_launches > hier0, \
        "hierarchical kernel did not run on the declared 2-slice mesh"
    for a, b in zip(flat, hier):
        _bitwise_equal(a, b)


@pytest.mark.parametrize("slices", [2, 4])
def test_hierarchical_slice_counts(hvd, monkeypatch, slices):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    n = hvd.size()
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", str(slices))
    mk.set_enabled(True)
    # Ragged flat length (13 not divisible by ici_size) exercises the
    # pad/unpad inside the kernel.
    pr = hvd.shard(np.arange(n * 13, dtype=np.float32).reshape(n, 13))
    out = np.asarray(hvd.allreduce(
        pr, average=False, name=f"mkhier.s{slices}"))
    ref = np.broadcast_to(
        np.arange(n * 13, dtype=np.float32).reshape(n, 13).sum(axis=0),
        (n, 13))
    np.testing.assert_array_equal(out, ref)


def test_hierarchical_dcn_compression(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    n = hvd.size()
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "bf16")
    mk.set_enabled(True)
    # Small integers: partial sums fit bf16's mantissa exactly, so the
    # compressed DCN leg is still exact here (the general case is
    # lossy by design — that is the bandwidth trade).
    pr = hvd.shard(np.ones((n, 8), np.float32))
    out = np.asarray(hvd.allreduce(pr, average=False, name="mkdcn"))
    np.testing.assert_array_equal(out, np.full((n, 8), float(n)))


def test_hierarchical_off_by_default(hvd):
    hier0 = mk.stats.hier_launches
    mk.set_enabled(True)
    n = hvd.size()
    hvd.allreduce(hvd.shard(np.ones((n, 4), np.float32)),
                  average=False, name="mkflat")
    assert mk.stats.hier_launches == hier0


def test_replica_hierarchy_detection(monkeypatch):
    from horovod_tpu.core import topology

    devs = jax.devices()
    assert topology.replica_hierarchy(devs) is None  # flat CPU mesh
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    h = topology.replica_hierarchy(devs)
    assert h is not None and h.n_slices == 2
    assert h.ici_size == len(devs) // 2
    assert h.ici_groups[0] == tuple(range(h.ici_size))
    assert h.dcn_groups[0] == (0, h.ici_size)
    # Off wins over declared slices.
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "off")
    assert topology.replica_hierarchy(devs) is None
    # Non-tiling virtual slice count degrades to flat.
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "3")
    assert topology.replica_hierarchy(devs) is None
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "bogus")
    with pytest.raises(ValueError):
        topology.replica_hierarchy(devs)


# ---------------------------------------------------------------------------
# Executable cache
# ---------------------------------------------------------------------------

def test_executable_reuse_across_cycles(hvd):
    n = hvd.size()
    inputs = [hvd.shard(np.ones((n, 8), np.float32)) for _ in range(3)]
    mk.set_enabled(True)

    def cycle(i):
        return hvd.grouped_allreduce(inputs, average=True,
                                     name=f"mkreuse.{i}")

    cycle(0)
    builds0, hits0 = mk.stats.builds, mk.stats.cache_hits
    cycle(1)  # same structure, different names → same executable
    assert mk.stats.builds == builds0, \
        "steady-state cycle recompiled its megakernel"
    assert mk.stats.cache_hits > hits0


def test_plan_digest_recorded(hvd):
    n = hvd.size()
    mk.set_enabled(True)
    x = hvd.shard(np.ones((n, 7), np.float32))
    hvd.allreduce(x, average=True, name="mkdigest")
    # The compiled executable is recorded under the PR 2 fusion-plan
    # digest: digest → spec → digest round-trips.
    with mk._lock:
        digests = dict(mk._digests)
    assert digests, "no plan digest recorded for a cold compile"
    for spec, digest in digests.items():
        assert mk.spec_for_digest(digest) == spec


def test_fusion_threshold_flushes_executables(hvd):
    import horovod_tpu.core.state as state_mod

    mk.set_enabled(True)
    x = np.ones(4, np.float32)
    hvd.allreduce(x, average=True, name="mkflush.1")
    assert mk.cache_size() > 0
    flushes0 = mk.stats.flushes
    st = state_mod.global_state()
    st.coordinator.set_fusion_threshold(32 << 20)
    assert mk.cache_size() == 0
    assert mk.stats.flushes > flushes0
    # And the executor rebuilds transparently afterwards.
    out = np.asarray(hvd.allreduce(x, average=True, name="mkflush.2"))
    np.testing.assert_array_equal(out, x)


# ---------------------------------------------------------------------------
# Satellite folds + vectorized ragged allgather
# ---------------------------------------------------------------------------

def test_ragged_allgather_vectorized(hvd):
    n = hvd.size()
    sizes = [3, 0, 2, 1, 4, 2, 1, 3][:n]
    parts = [np.arange(s * 2, dtype=np.float32).reshape(s, 2) + 100 * i
             for i, s in enumerate(sizes)]
    out = np.asarray(hvd.allgather(list(parts), name="mkragged"))
    np.testing.assert_array_equal(out, np.concatenate(parts, axis=0))


def test_ragged_allgather_all_empty(hvd):
    n = hvd.size()
    parts = [np.zeros((0, 3), np.float32) for _ in range(n)]
    out = np.asarray(hvd.allgather(list(parts), name="mkempty"))
    assert out.shape == (0, 3)


def test_reducescatter_average_fold(hvd):
    n = hvd.size()
    x = np.arange(n * 2 * 3, dtype=np.float32).reshape(n * 2, 3)
    out = np.asarray(hvd.reducescatter(x, average=True, name="mkrs.f"))
    ref = np.stack([x[r * 2:(r + 1) * 2] for r in range(n)])
    np.testing.assert_allclose(out, ref)  # mean of n identical copies
    # Integer AVERAGE floor-divides, matching _divide's contract.
    xi = np.full((n, 2), 5, np.int32)
    outi = np.asarray(hvd.reducescatter(xi, op=hvd.Average,
                                        name="mkrs.i"))
    np.testing.assert_array_equal(
        outi, np.full((n, 1, 2), (5 * n) // n, np.int32))


def test_broadcast_replicated_fold(hvd):
    x = np.arange(5.0, dtype=np.float32)
    out = np.asarray(hvd.broadcast(x, 0, name="mkbc.f"))
    np.testing.assert_array_equal(out, x)
    xi = np.arange(5, dtype=np.int32)
    outi = np.asarray(hvd.broadcast(xi, 0, name="mkbc.i"))
    np.testing.assert_array_equal(outi, xi)


def test_eager_fallback_disables_megakernel(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    mk.set_enabled(False)
    launches0 = mk.stats.launches
    n = hvd.size()
    out = np.asarray(hvd.allreduce(
        hvd.shard(np.ones((n, 4), np.float32)), average=True,
        name="mkoff"))
    np.testing.assert_array_equal(out, np.ones((n, 4), np.float32))
    assert mk.stats.launches == launches0


# ---------------------------------------------------------------------------
# Quantized allreduce (ISSUE 6): int8/int4 wire reduction inside the
# megakernels, stochastic rounding, error-feedback residuals
# ---------------------------------------------------------------------------

from horovod_tpu.ops import compression as comp  # noqa: E402


def _rows_of(base, n):
    return np.concatenate([t.reshape(n, -1) for t in base], axis=1)


def _single_group_steps(hvd, inputs, base_name, op, steps=2, attempts=5):
    """Run ``steps`` grouped cycles under FRESH names until every cycle
    of an attempt landed in exactly ONE fused launch.  A concurrent
    background tick can legally split a group across two fused
    responses (see grouped_allreduce_async); the eager-quantized
    reference models the single-group packing, so a split attempt is
    retried rather than mis-compared."""
    for attempt in range(attempts):
        name = f"{base_name}.a{attempt}"
        results = []
        clean = True
        for _ in range(steps):
            launches0 = mk.stats.launches
            outs = hvd.grouped_allreduce(inputs, op=op, name=name)
            clean &= (mk.stats.launches - launches0) == 1
            results.append(outs)
        if clean:
            return results
    pytest.skip("background tick split every attempt's fusion group")


@pytest.mark.parametrize("codec", ["int8", "int4"])
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
def test_quantized_matches_eager_reference(hvd, monkeypatch, codec, dtype):
    """The fused quantized kernel must equal the eager-quantized
    REFERENCE (ops/compression.reference_allreduce) BITWISE — per
    codec, per dtype — including the error-feedback chain across two
    steps."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", codec)
    n = hvd.size()
    dt = jnp.bfloat16 if dtype == "bfloat16" else np.float32
    rng = np.random.default_rng(3)
    base = [np.asarray(jnp.asarray(
        rng.standard_normal((n, 48))).astype(dt)) for _ in range(3)]
    inputs = [hvd.shard(t) for t in base]
    rows = jnp.concatenate(
        [jnp.asarray(t).reshape(n, -1) for t in base], axis=1)
    fmt = comp.wire_format(codec)
    mk.set_enabled(True)

    outs, outs2 = _single_group_steps(
        hvd, inputs, f"qref.{codec}.{dtype}", hvd.Sum, steps=2)
    ref, res = comp.reference_allreduce(rows, fmt, 0)
    got = np.concatenate([np.asarray(o)[0].reshape(-1) for o in outs])
    assert np.asarray(ref).tobytes() == got.tobytes()

    # Step 2: the residual state carried by the executor must chain
    # exactly like the reference's.
    ref2, _ = comp.reference_allreduce(rows, fmt, 1, residuals=res)
    got2 = np.concatenate([np.asarray(o)[0].reshape(-1) for o in outs2])
    assert np.asarray(ref2).tobytes() == got2.tobytes()


def test_quantized_eager_executor_matches_megakernel(hvd, monkeypatch):
    """HVD_TPU_MEGAKERNEL=0 keeps the quantized semantics: the eager
    fallback runs the reference math with the same residual store and
    tick counter, so eager ≡ fused bitwise (fresh names → fresh
    ticks)."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    rng = np.random.default_rng(4)
    base = [rng.standard_normal((n, 32)).astype(np.float32)
            for _ in range(3)]
    inputs = [hvd.shard(t) for t in base]

    def tick_keys():
        with mk._lock:
            return len(mk._ticks)

    # Both legs must pack as ONE group (a background-tick split changes
    # the quantized grouping — see _single_group_steps); each clean leg
    # mints exactly one new tick key.
    for attempt in range(5):
        mk.set_enabled(False)
        t0 = tick_keys()
        eager = [np.asarray(o) for o in hvd.grouped_allreduce(
            inputs, average=True, name=f"qeager.e{attempt}")]
        eager_clean = tick_keys() - t0 == 1
        mk.set_enabled(True)
        t0 = tick_keys()
        fused = [np.asarray(o) for o in hvd.grouped_allreduce(
            inputs, average=True, name=f"qeager.m{attempt}")]
        if eager_clean and tick_keys() - t0 == 1:
            break
    else:
        pytest.skip("background tick split every attempt's group")
    for a, b in zip(eager, fused):
        _bitwise_equal(a, b)


def test_quantized_replicated_layout(hvd, monkeypatch):
    """Replicated (sp_rep) contributions quantize with SHARED noise so
    the result stays replicated; matches the reference's shared-noise
    mode bitwise."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    rng = np.random.default_rng(5)
    x = rng.standard_normal(64).astype(np.float32)
    mk.set_enabled(True)
    out = np.asarray(hvd.allreduce(x.copy(), average=False,
                                   name="qrep.1"))
    rows = np.broadcast_to(x[None], (n, 64))
    ref, _ = comp.reference_allreduce(rows, comp.wire_format("int8"), 0,
                                      shared_noise=True)
    assert np.asarray(ref).tobytes() == out.tobytes()


def test_quantized_process_set(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    ps = hvd.add_process_set([0, 2, 5])
    x = np.linspace(-2, 2, 48).astype(np.float32)
    mk.set_enabled(True)
    out = np.asarray(hvd.allreduce(x.copy(), average=False, name="qps.1",
                                   process_set=ps))
    rows = np.broadcast_to(x[None], (3, 48))
    ref, _ = comp.reference_allreduce(rows, comp.wire_format("int8"), 0,
                                      shared_noise=True)
    assert np.asarray(ref).tobytes() == out.tobytes()
    hvd.remove_process_set(ps)


def test_stochastic_rounding_bitwise_deterministic(hvd, monkeypatch):
    """Fixed HVD_TPU_QUANT_SEED + executor state reset ⇒ bitwise
    identical results across re-runs (the noise is a pure function of
    (seed, per-group tick, position))."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    monkeypatch.setenv("HVD_TPU_QUANT_SEED", "1234")
    n = hvd.size()
    rng = np.random.default_rng(6)
    base = rng.standard_normal((n, 40)).astype(np.float32)
    x = hvd.shard(base)
    mk.set_enabled(True)

    def two_steps():
        a = np.asarray(hvd.allreduce(x, average=True, name="qdet"))
        b = np.asarray(hvd.allreduce(x, average=True, name="qdet"))
        return a, b

    a1, b1 = two_steps()
    mk.flush("test: determinism reset")  # clears residuals AND ticks
    a2, b2 = two_steps()
    _bitwise_equal(a1, a2)
    _bitwise_equal(b1, b2)
    # A different seed must change the bits (the test has teeth).
    monkeypatch.setenv("HVD_TPU_QUANT_SEED", "99")
    mk.flush("test: reseed")
    a3 = np.asarray(hvd.allreduce(x, average=True, name="qdet"))
    assert np.asarray(a3).tobytes() != a1.tobytes()


def test_error_feedback_residual_carryover(hvd, monkeypatch):
    """EF makes the RUNNING MEAN of repeated reductions of the same
    value converge on the exact answer (the error telescopes); with EF
    off the quantization error persists.  Also: the executor owns
    exactly one flat residual buffer per group."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    rng = np.random.default_rng(8)
    base = rng.standard_normal((n, 33)).astype(np.float32)
    exact = base.sum(axis=0)
    x = hvd.shard(base)
    mk.set_enabled(True)
    res0 = mk.residual_count()

    outs = [np.asarray(hvd.allreduce(x, average=False, name="qef"))[0]
            for _ in range(8)]
    assert mk.residual_count() == res0 + 1
    running = np.mean(outs, axis=0)
    first_err = np.abs(outs[0] - exact).max()
    mean_err = np.abs(running - exact).max()
    assert mean_err < first_err or first_err == 0.0

    # EF off: no residual state is created.
    monkeypatch.setenv("HVD_TPU_QUANT_ERROR_FEEDBACK", "0")
    mk.flush("test: ef off")
    np.asarray(hvd.allreduce(x, average=False, name="qnoef"))
    assert mk.residual_count() == 0


def test_residual_flush_on_fusion_threshold_change(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    import horovod_tpu.core.state as state_mod

    n = hvd.size()
    mk.set_enabled(True)
    x = hvd.shard(np.ones((n, 24), np.float32))
    np.asarray(hvd.allreduce(x, average=True, name="qflush"))
    assert mk.residual_count() > 0
    st = state_mod.global_state()
    st.coordinator.set_fusion_threshold(16 << 20)
    assert mk.residual_count() == 0, \
        "plan invalidation must flush the error-feedback residuals"
    assert mk.cache_size() == 0


def test_compression_state_checkpoint_roundtrip(hvd, monkeypatch):
    """compression_state()/load_compression_state(): restoring a
    snapshot resumes the EF chain exactly — the replayed step is
    bitwise identical to the original continuation."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    rng = np.random.default_rng(9)
    x = hvd.shard(rng.standard_normal((n, 48)).astype(np.float32))
    mk.set_enabled(True)
    np.asarray(hvd.allreduce(x, average=False, name="qckpt"))  # step 0
    snap = hvd.compression_state()
    assert snap["residuals"] and snap["ticks"]
    out1 = np.asarray(hvd.allreduce(x, average=False, name="qckpt"))
    mk.flush("test: simulate relaunch")
    hvd.load_compression_state(snap)
    out1b = np.asarray(hvd.allreduce(x, average=False, name="qckpt"))
    _bitwise_equal(out1, out1b)


def test_per_tensor_policy_partitions_groups(hvd, monkeypatch):
    """Per-tensor selection: rules route one tensor uncompressed while
    its groupmates quantize — the fusion group splits into one fused
    launch per wire format, and the uncompressed tensor stays exact."""
    monkeypatch.delenv("HVD_TPU_COMPRESSION", raising=False)
    n = hvd.size()
    rng = np.random.default_rng(10)
    emb = rng.standard_normal((n, 64)).astype(np.float32)
    # Integer-valued floats: exact under any psum association, so the
    # uncompressed bucket can be checked for EXACT equality.
    ln = np.tile(np.arange(32, dtype=np.float32), (n, 1))
    inputs = [hvd.shard(emb), hvd.shard(ln)]
    hvd.set_compression(default="int8",
                        rules=[(r"\.ln_scale$", "none")])
    try:
        mk.set_enabled(True)
        launches0 = mk.stats.launches
        quant0 = mk.stats.quant_launches
        hs = [hvd.allreduce_async(inputs[0], op=hvd.Sum,
                                  name="qpol.emb"),
              hvd.allreduce_async(inputs[1], op=hvd.Sum,
                                  name="qpol.ln_scale")]
        outs = [hvd.synchronize(h) for h in hs]
        assert mk.stats.launches - launches0 == 2, \
            "mixed-format group must split into one launch per format"
        assert mk.stats.quant_launches - quant0 == 1
        # The rule-matched tensor rode the exact psum.
        np.testing.assert_array_equal(
            np.asarray(outs[1])[0], ln[0] * n)
        # The embedding was quantized (teeth: its result differs from
        # the exact sum but stays within the codebook's error bound).
        got = np.asarray(outs[0])[0]
        exact = emb.sum(axis=0)
        assert got.tobytes() != exact.tobytes()
        assert np.abs(got - exact).max() < 1.0
    finally:
        hvd.set_compression()


def test_quantized_hierarchical_per_leg(hvd, monkeypatch):
    """Per-leg composition on a 2-virtual-slice mesh: ICI full
    precision + DCN inheriting the group's int8 (the default), then an
    explicitly quantized ICI leg — both within the codebook error
    bound, deterministic under a fixed seed, still one dispatch."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    n = hvd.size()
    rng = np.random.default_rng(11)
    base = rng.standard_normal((n, 80)).astype(np.float32)
    exact = base.sum(axis=0)
    x = hvd.shard(base)
    mk.set_enabled(True)

    hier0 = mk.stats.hier_launches
    out = np.asarray(hvd.allreduce(x, average=False, name="qhier.dcn"))
    assert mk.stats.hier_launches > hier0
    assert np.abs(out[0] - exact).max() < 1.0
    out_b = np.asarray(hvd.allreduce(x, average=False, name="qhier.dcn2"))
    # Same (seed, tick 0) under different names: the hierarchical
    # path's noise is name-independent, so equal inputs reduce equally.
    _bitwise_equal(out, out_b)

    monkeypatch.setenv("HVD_TPU_ICI_COMPRESS", "int8")
    out_ici = np.asarray(hvd.allreduce(x, average=False,
                                       name="qhier.ici"))
    assert np.abs(out_ici[0] - exact).max() < 1.5
    assert out_ici.tobytes() != out.tobytes()  # different pipeline


def test_dcn_quant_without_policy(hvd, monkeypatch):
    """HVD_TPU_DCN_COMPRESS=int8 quantizes ONLY the cross-slice leg —
    no policy, no residuals; the ICI legs stay full precision."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "int8")
    n = hvd.size()
    rng = np.random.default_rng(12)
    base = rng.standard_normal((n, 64)).astype(np.float32)
    x = hvd.shard(base)
    mk.set_enabled(True)
    res0 = mk.residual_count()
    quant0 = mk.stats.quant_launches
    out = np.asarray(hvd.allreduce(x, average=False, name="qdcnonly"))
    assert mk.stats.quant_launches > quant0
    assert mk.residual_count() == res0  # leg codecs carry no EF state
    assert np.abs(out[0] - base.sum(axis=0)).max() < 1.0


def test_wire_bytes_accounting_and_telemetry(hvd, monkeypatch):
    """Bytes-on-wire accounting: int8 must record ~4x fewer wire than
    logical bytes, the collective.wire_bytes histogram must see the
    launch, and the compression.ratio gauge must report the ratio."""
    from horovod_tpu import telemetry

    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    mk.set_enabled(True)
    w0, l0 = mk.stats.wire_bytes, mk.stats.logical_bytes
    x = hvd.shard(np.ones((n, 256), np.float32))
    np.asarray(hvd.allreduce(x, average=True, name="qwire"))
    wire = mk.stats.wire_bytes - w0
    logical = mk.stats.logical_bytes - l0
    assert logical > 0 and wire > 0
    ratio = logical / wire
    assert 3.0 <= ratio <= 4.0, ratio
    snap = telemetry.metrics()
    assert snap["collective.wire_bytes"]["count"] >= 1
    assert snap["compression.ratio"]["value"] >= 1.0
    assert snap["megakernel.quant_launches"]["value"] >= 1


def test_quantized_one_dispatch_per_group(hvd, monkeypatch):
    """The tentpole's zero-extra-dispatch claim: quantize → exchange →
    dequantize → residual update all compile into the ONE fused
    executable per group, steady state included."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    inputs = [hvd.shard(np.full((n, 32), float(j + 1), np.float32))
              for j in range(4)]
    mk.set_enabled(True)

    def cyc():
        hs = [hvd.allreduce_async(t, average=True, name=f"qdisp.{j}")
              for j, t in enumerate(inputs)]
        return [hvd.synchronize(h) for h in hs]

    cyc()
    cyc()
    launches0 = mk.stats.launches
    with xla_dispatch.exact_scope():
        with xla_dispatch.record(all_threads=True) as scope:
            cyc()
    groups = mk.stats.launches - launches0
    assert groups >= 1
    assert scope.count == groups, (
        f"quantized steady-state cycle issued {scope.count} dispatches "
        f"for {groups} fusion group(s)")


def test_int_dtypes_and_non_sum_ops_never_quantize(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = hvd.size()
    mk.set_enabled(True)
    quant0 = mk.stats.quant_launches
    xi = hvd.shard(np.full((n, 32), 3, np.int32))
    outi = np.asarray(hvd.allreduce(xi, average=False, name="qint"))
    np.testing.assert_array_equal(outi[0], np.full(32, 3 * n))
    xf = hvd.shard(np.arange(n * 32, dtype=np.float32).reshape(n, 32))
    outm = np.asarray(hvd.allreduce(xf, op=hvd.Max, name="qmax"))
    np.testing.assert_array_equal(
        outm[0], np.arange(n * 32, dtype=np.float32).reshape(n, 32)
        .max(axis=0))
    assert mk.stats.quant_launches == quant0


def test_dcn_none_opts_out_of_inheritance(hvd, monkeypatch):
    """An EXPLICIT HVD_TPU_DCN_COMPRESS=none pins the DCN leg to full
    precision even when the group's policy is quantized (unset = the
    inheritance default) — review finding: the opt-out must exist."""
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL", "on")
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "none")
    n = hvd.size()
    mesh_key = tuple(jax.devices())
    fmt = mk._compression.wire_format("int8")
    hier = mk.hierarchy_for(mesh_key, "psum", np.float32, group_fmt=fmt)
    assert hier is not None
    assert hier.dcn_quant is None and hier.wire_dtype is None
    # Unset: the group's quantized format inherits onto the DCN leg.
    monkeypatch.delenv("HVD_TPU_DCN_COMPRESS")
    hier2 = mk.hierarchy_for(mesh_key, "psum", np.float32,
                             group_fmt=fmt)
    assert hier2.dcn_quant is not None \
        and hier2.dcn_quant.name == "int8"
    # And end to end: the pinned-none run reduces exactly for
    # integer-valued floats on the ICI+DCN full-precision pipeline...
    base = np.arange(n * 32, dtype=np.float32).reshape(n, 32)
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "none")
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "none")
    out = np.asarray(hvd.allreduce(hvd.shard(base), average=False,
                                   name="qoptout"))
    np.testing.assert_array_equal(out[0], base.sum(axis=0))
