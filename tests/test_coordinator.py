"""Coordinator unit tests: readiness counting, fusion planning, stall
detection, wire round-trip (≙ the machinery of reference
operations.cc:222-461, :1072-1115, :1328-1374 and mpi_message.cc)."""

import time

import numpy as np
import pytest

from horovod_tpu.native import lib as _native_lib
from horovod_tpu.ops.coordinator import (NativeCoordinator, PyCoordinator,
                                         STALL_WARNING_SECONDS)
from horovod_tpu.ops.wire import (DataType, ReduceOp, Request, RequestType,
                                  Response, ResponseType, pack_response_list,
                                  unpack_response_list)


@pytest.fixture(params=["py", "native"])
def make_coord(request):
    """Both coordinator implementations must pass the identical matrix —
    the Python one is the executable spec for native/coordinator.cc.
    Yields a factory that closes every instance at teardown (the native
    one owns a C++ allocation)."""
    if request.param == "native":
        if not (_native_lib.NATIVE
                and hasattr(_native_lib.raw(), "hvd_coord_fetch_responses")):
            pytest.skip("native library not built")
        ctor = NativeCoordinator
    else:
        ctor = PyCoordinator
    made = []

    def factory(size, fusion_threshold):
        c = ctor(size, fusion_threshold)
        made.append(c)
        return c

    yield factory
    for c in made:
        c.close()


def _req(rank, name, shape=(4,), op=RequestType.ALLREDUCE,
         dtype=DataType.FLOAT32, root=-1, device=-1,
         red=ReduceOp.AVERAGE, splits=()):
    return Request(rank, op, dtype, name, root, device, shape, red, 0,
                   splits)


def test_readiness_counting(make_coord):
    """A tensor becomes ready only when all replicas submitted
    (≙ IncrementTensorCount, operations.cc:222-247)."""
    c = make_coord(4, 1 << 20)
    for r in range(3):
        assert c.submit(_req(r, "t")) is False
    assert c.submit(_req(3, "t")) is True
    resps = c.poll_responses({"t": 16})
    assert len(resps) == 1
    assert resps[0].response_type == ResponseType.ALLREDUCE
    assert resps[0].tensor_names == ["t"]


def test_duplicate_rank_rejected(make_coord):
    c = make_coord(2, 0)
    c.submit(_req(0, "t"))
    with pytest.raises(ValueError):
        c.submit(_req(0, "t"))


def test_fusion_same_dtype_under_threshold(make_coord):
    """Two small float32 allreduces fuse into one response; an int32 one
    does not join them (fusion requires matching dtype, as the reference's
    fusion-buffer requires one dtype per buffer)."""
    c = make_coord(2, 1024)
    for name in ("a", "b"):
        for r in range(2):
            c.submit(_req(r, name))
    for r in range(2):
        c.submit(_req(r, "c", dtype=DataType.INT32))
    resps = c.poll_responses({"a": 16, "b": 16, "c": 16})
    fused = [r for r in resps if len(r.tensor_names) > 1]
    assert len(fused) == 1
    assert sorted(fused[0].tensor_names) == ["a", "b"]


def test_fusion_threshold_respected(make_coord):
    """Tensors stop fusing once the byte budget is exhausted
    (≙ operations.cc:1328-1360; HOROVOD_FUSION_THRESHOLD semantics)."""
    c = make_coord(1, 100)
    for name in ("a", "b", "c"):
        c.submit(_req(0, name))
    # a=60B, b=60B (won't fit with a), c=30B (fits with a: 90 <= 100).
    resps = c.poll_responses({"a": 60, "b": 60, "c": 30})
    names = [tuple(sorted(r.tensor_names)) for r in resps
             if r.response_type == ResponseType.ALLREDUCE]
    assert ("a", "c") in names
    assert ("b",) in names


def test_fusion_disabled_with_zero_threshold(make_coord):
    c = make_coord(1, 0)
    for name in ("a", "b"):
        c.submit(_req(0, name))
    resps = c.poll_responses({"a": 8, "b": 8})
    assert all(len(r.tensor_names) == 1 for r in resps)


def test_stall_detection(make_coord):
    """Tensors pending longer than the threshold are reported with ready
    and missing replica lists (≙ CheckForStalledTensors,
    operations.cc:1072-1115).  Real timestamps + a tiny threshold so the
    same test drives both implementations (the native one keeps its own
    clock)."""
    c = make_coord(4, 0)
    c.submit(_req(0, "stuck"))
    c.submit(_req(2, "stuck"))
    time.sleep(0.05)
    warnings = c.check_stalled(threshold=0.01)
    assert len(warnings) == 1
    w = warnings[0]
    assert "stuck" in w
    assert "[0, 2]" in w       # ready replicas
    assert "[1, 3]" in w       # missing replicas
    # Under the threshold: no warning.
    assert c.check_stalled(threshold=30.0) == []


def test_wire_roundtrip():
    """Request/Response serialize → parse losslessly (≙ the flatbuffers
    round-trip, mpi_message.cc:118-163, :290-324)."""
    r = Request(3, RequestType.ALLGATHER, DataType.BFLOAT16,
                "layer1/weights:0", root_rank=2, device=5,
                tensor_shape=(128, 256, 3))
    buf = r.pack()
    r2, off = Request.unpack(buf)
    assert off == len(buf)
    assert r2 == r

    resp = Response(ResponseType.ALLGATHER, ["a", "b"], "",
                    devices=[0, 1, 2], tensor_sizes=[5, 7, 9])
    buf = pack_response_list([resp, Response(ResponseType.ERROR, ["x"],
                                             "boom message")])
    out = unpack_response_list(buf)
    assert out[0] == resp
    assert out[1].error_message == "boom message"
    assert out[1].response_type == ResponseType.ERROR


def test_device_mismatch_detected(make_coord):
    """Host tensor on one replica, device tensor on another → error
    (≙ the CPU-vs-GPU placement mismatch test, test_tensorflow.py:459+)."""
    c = make_coord(2, 0)
    c.submit(_req(0, "t", device=-1))
    c.submit(_req(1, "t", device=0))
    resps = c.poll_responses({"t": 16})
    assert resps[0].response_type == ResponseType.ERROR
    assert "device" in resps[0].error_message


def test_py_native_response_parity_fuzz():
    """Randomized request batches produce byte-identical (packed) response
    lists from both coordinators — the 'executable spec' claim, verified
    in both directions."""
    if not (_native_lib.NATIVE
            and hasattr(_native_lib.raw(), "hvd_coord_fetch_responses")):
        pytest.skip("native library not built")
    rng = np.random.RandomState(0)
    dtypes = [DataType.FLOAT32, DataType.INT32, DataType.BFLOAT16,
              DataType.UINT32, DataType.UINT64]
    ops = [RequestType.ALLREDUCE, RequestType.ALLGATHER,
           RequestType.BROADCAST, RequestType.REDUCESCATTER]
    for trial in range(30):
        size = int(rng.randint(1, 5))
        py = PyCoordinator(size, int(rng.choice([0, 64, 1024, 1 << 20])))
        nat = NativeCoordinator(py.size, py.fusion_threshold)
        sizes_bytes = {}
        for t in range(int(rng.randint(1, 6))):
            name = f"t{trial}.{t}"
            op = ops[rng.randint(len(ops))]
            sizes_bytes[name] = int(rng.randint(1, 200))
            # One shape/dtype/root per tensor so agreement (and therefore
            # successful fused responses) is the common case; disagreement
            # is injected explicitly to exercise the ERROR paths.
            base_shape = (int(rng.randint(1, 4)), 3)
            base_dtype = dtypes[rng.randint(len(dtypes))]
            base_red = ReduceOp(int(rng.randint(0, 6)))
            root = int(rng.randint(0, size))
            for r in range(size):
                shape, dt, red = base_shape, base_dtype, base_red
                if op in (RequestType.ALLGATHER,
                          RequestType.ALLTOALL) and rng.rand() < 0.5:
                    # Ragged dim 0 is legal for allgather/alltoall.
                    shape = (int(rng.randint(1, 6)), shape[1])
                if rng.rand() < 0.1:
                    shape = (shape[0], 4)
                if rng.rand() < 0.1:
                    dt = dtypes[(dtypes.index(dt) + 1) % len(dtypes)]
                if rng.rand() < 0.1:
                    red = ReduceOp((int(red) + 1) % 6)
                splits = ()
                if op == RequestType.ALLTOALL and rng.rand() < 0.6:
                    # Valid or (10%) deliberately invalid splits.
                    cuts = sorted(rng.randint(0, shape[0] + 1, size - 1)) \
                        if size > 1 else []
                    splits = tuple(
                        b - a for a, b in zip([0] + list(cuts),
                                              list(cuts) + [shape[0]]))
                    if rng.rand() < 0.1:
                        splits = splits + (1,)
                py_req = _req(r, name, shape=shape, op=op, dtype=dt,
                              root=root, red=red, splits=splits)
                py.submit(py_req)
                nat.submit(py_req)
        py_resps = py.poll_responses(sizes_bytes)
        nat_resps = nat.poll_responses(sizes_bytes)
        assert pack_response_list(py_resps) == pack_response_list(
            nat_resps), (trial, py_resps, nat_resps)
        nat.close()


def test_wire_uint32_uint64_roundtrip(make_coord):
    """Keras seed-generator variables are uint32; the wire and BOTH
    coordinator implementations must carry the extended dtypes."""
    from horovod_tpu.ops.wire import dtype_of, dtype_size

    r = Request(0, RequestType.BROADCAST, DataType.UINT32, "seed",
                root_rank=0, tensor_shape=(2,))
    r2, _ = Request.unpack(r.pack())
    assert r2.tensor_type == DataType.UINT32
    assert dtype_of(np.dtype(np.uint32)) == DataType.UINT32
    assert dtype_of(np.dtype(np.uint64)) == DataType.UINT64
    assert dtype_size(DataType.UINT64) == 8
    # Drive a uint32 negotiation through the coordinator, including the
    # mismatch error message (exercises native DataTypeName).
    c = make_coord(2, 0)
    c.submit(_req(0, "seed.t", dtype=DataType.UINT32))
    c.submit(_req(1, "seed.t", dtype=DataType.UINT64))
    resps = c.poll_responses({"seed.t": 8})
    assert resps[0].response_type == ResponseType.ERROR
    assert "uint32" in resps[0].error_message
    assert "uint64" in resps[0].error_message


def test_withdraw_errors_pending_op(make_coord):
    """withdraw() (round 4) drops the pending entry and queues an ERROR
    response so every rank fails the op promptly — the reference could
    only hang when a rank gave up (operations.cc:1290-1326)."""
    c = make_coord(2, 1 << 20)
    assert c.submit(_req(0, "w.op")) is False
    c.withdraw("w.op", 0)
    resps = c.poll_responses({"w.op": 16})
    assert len(resps) == 1
    assert resps[0].response_type == ResponseType.ERROR
    assert resps[0].tensor_names == ["w.op"]
    assert "was abandoned: rank 0" in resps[0].error_message
    # Entry gone: the name is reusable; a late peer submit starts a
    # FRESH negotiation instead of corrupting the withdrawn one.
    assert c.submit(_req(1, "w.op")) is False


def test_withdraw_after_ready_is_noop(make_coord):
    """A withdrawal racing negotiation completion loses: the op is about
    to finish normally, so it does."""
    c = make_coord(2, 1 << 20)
    c.submit(_req(0, "done.op"))
    assert c.submit(_req(1, "done.op")) is True
    c.withdraw("done.op", 1)
    resps = c.poll_responses({"done.op": 16})
    assert len(resps) == 1
    assert resps[0].response_type == ResponseType.ALLREDUCE


def test_withdraw_packed_response_parity():
    """The withdrawal ERROR must pack byte-identically from both
    coordinator implementations (shared wire contract)."""
    if not (_native_lib.NATIVE
            and hasattr(_native_lib.raw(), "hvd_coord_withdraw")):
        pytest.skip("native library not built")
    py, nat = PyCoordinator(2, 1 << 20), NativeCoordinator(2, 1 << 20)
    try:
        for c in (py, nat):
            c.submit(_req(0, "p.op"))
            c.withdraw("p.op", 0)
        assert pack_response_list(py.poll_responses({"p.op": 16})) == \
            pack_response_list(nat.poll_responses({"p.op": 16}))
    finally:
        nat.close()


def _join_req(rank):
    return Request(rank, RequestType.JOIN, DataType.UINT8, "hvd.join")


def test_join_completes_pending_and_releases(make_coord):
    """hvd.join (post-v0.13): joined ranks count as ready for pending
    tensors (zero contributions at execution); the last join queues the
    release response carrying the last joining rank — AFTER the data
    responses of the same poll."""
    c = make_coord(3, 1 << 20)
    assert c.submit(_req(0, "t")) is False
    assert c.submit(_req(1, "t")) is False
    # Rank 2 joins instead of submitting: the tensor completes.
    assert c.submit(_join_req(2)) is False
    resps = c.poll_responses({"t": 16})
    assert [r.response_type for r in resps] == [ResponseType.ALLREDUCE]
    # Zero-fill metadata rides the response.
    assert resps[0].tensor_type == DataType.FLOAT32
    assert [tuple(s) for s in resps[0].tensor_shapes] == [(4,)]
    # A tensor submitted while a rank is joined completes immediately
    # once the live ranks report.
    assert c.submit(_req(0, "t2")) is False
    assert c.submit(_req(1, "t2")) is True
    c.submit(_join_req(0))
    assert c.submit(_join_req(1)) is True
    resps = c.poll_responses({"t2": 16})
    assert [r.response_type for r in resps] == \
        [ResponseType.ALLREDUCE, ResponseType.JOIN]
    assert list(resps[-1].tensor_sizes) == [1]  # last joining rank


def test_join_allgather_sizes_are_rank_indexed(make_coord):
    c = make_coord(2, 1 << 20)
    c.submit(_join_req(0))
    c.submit(_req(1, "g", shape=(3, 2), op=RequestType.ALLGATHER))
    resps = c.poll_responses({"g": 24})
    [r] = [r for r in resps if r.response_type == ResponseType.ALLGATHER]
    assert list(r.tensor_sizes) == [0, 3]  # joined rank 0 brings 0 rows


def test_join_broadcast_root_joined_errors(make_coord):
    c = make_coord(2, 1 << 20)
    c.submit(_join_req(0))
    c.submit(_req(1, "b", op=RequestType.BROADCAST, root=0))
    resps = c.poll_responses({"b": 16})
    [r] = [r for r in resps if r.response_type == ResponseType.ERROR]
    assert "has joined" in r.error_message


def test_broadcast_response_carries_root(make_coord):
    c = make_coord(2, 1 << 20)
    c.submit(_req(0, "b", op=RequestType.BROADCAST, root=1))
    c.submit(_req(1, "b", op=RequestType.BROADCAST, root=1))
    resps = c.poll_responses({"b": 16})
    assert resps[0].response_type == ResponseType.BROADCAST
    assert list(resps[0].tensor_sizes) == [1]


def test_reduce_op_mismatch_is_error(make_coord):
    """Ranks disagreeing on the reduce operator for one name must get
    the ERROR response (the post-v0.13 op= API; v0.13 hard-codes
    MPI_SUM so the case could not arise)."""
    c = make_coord(2, 1 << 20)
    c.submit(_req(0, "t", red=ReduceOp.SUM))
    c.submit(_req(1, "t", red=ReduceOp.MAX))
    (resp,) = c.poll_responses({"t": 16})
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched reduce operations" in resp.error_message
    assert "sum" in resp.error_message and "max" in resp.error_message


def test_fusion_groups_by_reduce_op(make_coord):
    """Same-dtype same-device allreduces with DIFFERENT reduce ops must
    not share a fusion buffer (a min cannot ride a sum reduction)."""
    c = make_coord(2, 1 << 20)
    for name, red in (("a", ReduceOp.SUM), ("b", ReduceOp.MAX),
                      ("c", ReduceOp.SUM)):
        for r in range(2):
            c.submit(_req(r, name, red=red))
    resps = c.poll_responses({"a": 16, "b": 16, "c": 16})
    groups = sorted(sorted(r.tensor_names) for r in resps)
    assert groups == [["a", "c"], ["b"]], groups
    by_first = {r.tensor_names[0]: r.reduce_op for r in resps}
    assert by_first["a"] == ReduceOp.SUM
    assert by_first["b"] == ReduceOp.MAX


def test_adasum_never_fuses(make_coord):
    """Adasum responses stay un-fused: the dot products are per-tensor
    scale adaptations, not elementwise reductions."""
    c = make_coord(2, 1 << 20)
    for name in ("a", "b"):
        for r in range(2):
            c.submit(_req(r, name, red=ReduceOp.ADASUM))
    resps = c.poll_responses({"a": 16, "b": 16})
    assert sorted(r.tensor_names[0] for r in resps) == ["a", "b"]
    assert all(len(r.tensor_names) == 1 for r in resps)


def test_non_sum_allreduce_with_joined_rank_is_error(make_coord):
    """A joined rank contributes zeros — an identity only for
    sum/average, so completing a min allreduce via a join must error."""
    c = make_coord(2, 1 << 20)
    c.submit(_req(0, "hvd.join", op=RequestType.JOIN, dtype=DataType.UINT8))
    c.submit(_req(1, "t", red=ReduceOp.MIN))
    resps = c.poll_responses({"t": 16})
    data = [r for r in resps if r.response_type != ResponseType.JOIN]
    assert data[0].response_type == ResponseType.ERROR
    assert "cannot complete after a rank has joined" in \
        data[0].error_message
    # sum/average still complete through the join.
    c2 = make_coord(2, 1 << 20)
    c2.submit(_req(0, "hvd.join", op=RequestType.JOIN,
                   dtype=DataType.UINT8))
    c2.submit(_req(1, "t2", red=ReduceOp.AVERAGE))
    resps = c2.poll_responses({"t2": 16})
    data = [r for r in resps if r.response_type != ResponseType.JOIN]
    assert data[0].response_type == ResponseType.ALLREDUCE


def test_reducescatter_validation_both_impls(make_coord):
    """Reducescatter (post-v0.13): shape and reduce-op mismatches get
    the ERROR response from BOTH coordinator implementations, and a
    matched pair yields a REDUCESCATTER response carrying the op."""
    c = make_coord(2, 0)
    c.submit(_req(0, "rs.shape", op=RequestType.REDUCESCATTER,
                  shape=(8,)))
    c.submit(_req(1, "rs.shape", op=RequestType.REDUCESCATTER,
                  shape=(4,)))
    (resp,) = c.poll_responses({})
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched reducescatter tensor shapes" in resp.error_message

    c2 = make_coord(2, 0)
    c2.submit(_req(0, "rs.op", op=RequestType.REDUCESCATTER,
                   red=ReduceOp.SUM))
    c2.submit(_req(1, "rs.op", op=RequestType.REDUCESCATTER,
                   red=ReduceOp.AVERAGE))
    (resp,) = c2.poll_responses({})
    assert resp.response_type == ResponseType.ERROR
    assert "Mismatched reduce operations" in resp.error_message

    c3 = make_coord(2, 0)
    c3.submit(_req(0, "rs.ok", op=RequestType.REDUCESCATTER,
                   red=ReduceOp.AVERAGE))
    c3.submit(_req(1, "rs.ok", op=RequestType.REDUCESCATTER,
                   red=ReduceOp.AVERAGE))
    (resp,) = c3.poll_responses({})
    assert resp.response_type == ResponseType.REDUCESCATTER
    assert resp.reduce_op == ReduceOp.AVERAGE


def test_reducescatter_refuses_joined_completion(make_coord):
    """A reducescatter completed via a join must error: the joined rank
    cannot receive its chunk (both implementations)."""
    c = make_coord(2, 0)
    c.submit(_req(0, "hvd.join", op=RequestType.JOIN,
                  dtype=DataType.UINT8))
    c.submit(_req(1, "rs.joined", op=RequestType.REDUCESCATTER))
    resps = c.poll_responses({})
    data = [r for r in resps if r.response_type != ResponseType.JOIN]
    assert data[0].response_type == ResponseType.ERROR
    assert "cannot complete after a rank has joined" in \
        data[0].error_message
