"""Coordinator unit tests: readiness counting, fusion planning, stall
detection, wire round-trip (≙ the machinery of reference
operations.cc:222-461, :1072-1115, :1328-1374 and mpi_message.cc)."""

import numpy as np
import pytest

from horovod_tpu.ops.coordinator import PyCoordinator, STALL_WARNING_SECONDS
from horovod_tpu.ops.wire import (DataType, Request, RequestType, Response,
                                  ResponseType, pack_response_list,
                                  unpack_response_list)


def _req(rank, name, shape=(4,), op=RequestType.ALLREDUCE,
         dtype=DataType.FLOAT32, root=-1, device=-1):
    return Request(rank, op, dtype, name, root, device, shape)


def test_readiness_counting():
    """A tensor becomes ready only when all replicas submitted
    (≙ IncrementTensorCount, operations.cc:222-247)."""
    c = PyCoordinator(size=4, fusion_threshold=1 << 20)
    for r in range(3):
        assert c.submit(_req(r, "t")) is False
    assert c.submit(_req(3, "t")) is True
    resps = c.poll_responses({"t": 16})
    assert len(resps) == 1
    assert resps[0].response_type == ResponseType.ALLREDUCE
    assert resps[0].tensor_names == ["t"]


def test_duplicate_rank_rejected():
    c = PyCoordinator(size=2, fusion_threshold=0)
    c.submit(_req(0, "t"))
    with pytest.raises(ValueError):
        c.submit(_req(0, "t"))


def test_fusion_same_dtype_under_threshold():
    """Two small float32 allreduces fuse into one response; an int32 one
    does not join them (fusion requires matching dtype, as the reference's
    fusion-buffer requires one dtype per buffer)."""
    c = PyCoordinator(size=2, fusion_threshold=1024)
    for name in ("a", "b"):
        for r in range(2):
            c.submit(_req(r, name))
    for r in range(2):
        c.submit(_req(r, "c", dtype=DataType.INT32))
    resps = c.poll_responses({"a": 16, "b": 16, "c": 16})
    fused = [r for r in resps if len(r.tensor_names) > 1]
    assert len(fused) == 1
    assert sorted(fused[0].tensor_names) == ["a", "b"]


def test_fusion_threshold_respected():
    """Tensors stop fusing once the byte budget is exhausted
    (≙ operations.cc:1328-1360; HOROVOD_FUSION_THRESHOLD semantics)."""
    c = PyCoordinator(size=1, fusion_threshold=100)
    for name in ("a", "b", "c"):
        c.submit(_req(0, name))
    # a=60B, b=60B (won't fit with a), c=30B (fits with a: 90 <= 100).
    resps = c.poll_responses({"a": 60, "b": 60, "c": 30})
    names = [tuple(sorted(r.tensor_names)) for r in resps
             if r.response_type == ResponseType.ALLREDUCE]
    assert ("a", "c") in names
    assert ("b",) in names


def test_fusion_disabled_with_zero_threshold():
    c = PyCoordinator(size=1, fusion_threshold=0)
    for name in ("a", "b"):
        c.submit(_req(0, name))
    resps = c.poll_responses({"a": 8, "b": 8})
    assert all(len(r.tensor_names) == 1 for r in resps)


def test_stall_detection():
    """Tensors pending longer than the threshold are reported with ready
    and missing replica lists (≙ CheckForStalledTensors,
    operations.cc:1072-1115)."""
    c = PyCoordinator(size=4, fusion_threshold=0)
    c.submit(_req(0, "stuck"), now=0.0)
    c.submit(_req(2, "stuck"), now=1.0)
    warnings = c.check_stalled(now=STALL_WARNING_SECONDS + 2.0)
    assert len(warnings) == 1
    w = warnings[0]
    assert "stuck" in w
    assert "[0, 2]" in w       # ready replicas
    assert "[1, 3]" in w       # missing replicas
    # Under the threshold: no warning.
    assert c.check_stalled(now=30.0) == []


def test_wire_roundtrip():
    """Request/Response serialize → parse losslessly (≙ the flatbuffers
    round-trip, mpi_message.cc:118-163, :290-324)."""
    r = Request(3, RequestType.ALLGATHER, DataType.BFLOAT16,
                "layer1/weights:0", root_rank=2, device=5,
                tensor_shape=(128, 256, 3))
    buf = r.pack()
    r2, off = Request.unpack(buf)
    assert off == len(buf)
    assert r2 == r

    resp = Response(ResponseType.ALLGATHER, ["a", "b"], "",
                    devices=[0, 1, 2], tensor_sizes=[5, 7, 9])
    buf = pack_response_list([resp, Response(ResponseType.ERROR, ["x"],
                                             "boom message")])
    out = unpack_response_list(buf)
    assert out[0] == resp
    assert out[1].error_message == "boom message"
    assert out[1].response_type == ResponseType.ERROR


def test_device_mismatch_detected():
    """Host tensor on one replica, device tensor on another → error
    (≙ the CPU-vs-GPU placement mismatch test, test_tensorflow.py:459+)."""
    c = PyCoordinator(size=2, fusion_threshold=0)
    c.submit(_req(0, "t", device=-1))
    c.submit(_req(1, "t", device=0))
    resps = c.poll_responses({"t": 16})
    assert resps[0].response_type == ResponseType.ERROR
    assert "device" in resps[0].error_message
