"""ZeRO-3 / FSDP-style fully-sharded training (parallel/fsdp.py).

The contract: identical training trajectory to plain replicated DP
(all_gather(param shards) + backward + reduce_scatter + sharded update
== psum + replicated update, for elementwise optimizers), with the
parameters AND optimizer state resident as 1/N-per-replica flat shards
between steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_api
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.parallel.fsdp import make_fsdp_train_step
from horovod_tpu.parallel.training import make_train_step, shard_batch


def _loss_fn(model):
    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)
    return loss_fn


@pytest.mark.parametrize("opt_ctor", [
    lambda: optax.sgd(0.1, momentum=0.9),
    lambda: optax.adam(1e-2),
])
def test_fsdp_matches_plain_dp(hvd, opt_ctor):
    """Same data, same steps: FSDP must track plain DP numerically."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    opt = opt_ctor()
    plain = make_train_step(loss_fn, opt, donate=False)
    p_ref, st_ref = params, opt.init(params)
    fstep = make_fsdp_train_step(loss_fn, opt_ctor(), donate=False)
    p_f, st_f = fstep.init(params)

    for _ in range(5):
        p_ref, st_ref, loss_ref = plain(p_ref, st_ref, batch)
        p_f, st_f, loss_f = fstep.step(p_f, st_f, batch)
    np.testing.assert_allclose(float(loss_f), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(fstep.full_params(p_f)),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_params_and_state_are_sharded(hvd):
    """Parameters and Adam's mu/nu live as flat replica-sharded vectors:
    each device holds 1/N of the (padded) parameter count.  This is the
    storage claim that distinguishes FSDP from ZeRO-1."""
    model = MnistMLP(hidden=32)
    params = init_params(model)
    n = len(jax.devices())
    total = sum(l.size for l in jax.tree_util.tree_leaves(params))
    padded = -(-total // n) * n

    fstep = make_fsdp_train_step(_loss_fn(model), optax.adam(1e-3))
    p_shard, st = fstep.init(params)
    assert p_shard.shape == (padded,)
    shard_rows = {s.data.shape[0] for s in p_shard.addressable_shards}
    assert shard_rows == {padded // n}, shard_rows
    vec_leaves = [l for l in jax.tree_util.tree_leaves(st) if l.ndim >= 1]
    assert vec_leaves, "expected adam mu/nu vector leaves"
    for leaf in vec_leaves:
        assert leaf.shape == (padded,)
        rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert rows == {padded // n}, rows


def test_fsdp_full_params_round_trips(hvd):
    """init -> full_params reproduces the original pytree exactly
    (layout sanity: shard slicing and unravel agree)."""
    model = MnistMLP(hidden=24)
    params = init_params(model)
    fstep = make_fsdp_train_step(_loss_fn(model), optax.sgd(0.1),
                                 donate=False)
    p_shard, _ = fstep.init(params)
    restored = fstep.full_params(p_shard)
    assert (jax.tree_util.tree_structure(restored)
            == jax.tree_util.tree_structure(params))
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(params)):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_training_converges(hvd):
    model = MnistMLP(hidden=64)
    params = init_params(model)
    fstep = make_fsdp_train_step(_loss_fn(model), optax.adam(1e-3))
    p_shard, st = fstep.init(params)
    images, labels = synthetic_mnist(256)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    losses = []
    for _ in range(30):
        p_shard, st, loss = fstep.step(p_shard, st, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_fsdp_with_state_matches_plain_dp(hvd):
    """Stateful variant (synchronized BatchNorm): tracks
    make_train_step_with_state on a BatchNorm MLP (the smallest model
    carrying running statistics — a conv stack adds only compile time
    here; ResNet itself is covered in test_resnet.py)."""
    from horovod_tpu.models.mnist import (MnistBNMLP, bn_mlp_loss_fn,
                                          init_bn_mlp, synthetic_mnist)
    from horovod_tpu.parallel.fsdp import make_fsdp_train_step_with_state
    from horovod_tpu.parallel.training import make_train_step_with_state

    model = MnistBNMLP(hidden=32)
    params, stats = init_bn_mlp(model)
    loss_fn = bn_mlp_loss_fn(model)
    images, labels = synthetic_mnist(16)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    opt = optax.sgd(0.1, momentum=0.9)
    plain = make_train_step_with_state(loss_fn, opt, donate=False)
    fstep = make_fsdp_train_step_with_state(
        loss_fn, optax.sgd(0.1, momentum=0.9), donate=False)
    p1, s1, o1 = params, stats, opt.init(params)
    p2, o2 = fstep.init(params)
    s2 = stats
    for _ in range(3):
        p1, s1, o1, l1 = plain(p1, s1, o1, batch)
        p2, s2, o2, l2 = fstep.step(p2, s2, o2, batch)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(fstep.full_params(p2)),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s2),
                    jax.tree_util.tree_leaves(s1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


def test_fsdp_composes_with_compression(hvd):
    """bf16-compressed reduce_scatter stays close to the exact step
    (also exercises DistributedOptimizer unwrap)."""
    from horovod_tpu.ops.compression import Compression

    model = MnistMLP(hidden=32)
    params = init_params(model)
    loss_fn = _loss_fn(model)
    images, labels = synthetic_mnist(64)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))

    exact = make_fsdp_train_step(loss_fn, optax.sgd(0.1), donate=False)
    dopt = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                        compression=Compression.bf16)
    comp = make_fsdp_train_step(loss_fn, dopt, donate=False)
    pe, se = exact.init(params)
    pc, sc = comp.init(params)
    pe, _, _ = exact.step(pe, se, batch)
    pc, _, _ = comp.step(pc, sc, batch)
    for a, b in zip(jax.tree_util.tree_leaves(comp.full_params(pc)),
                    jax.tree_util.tree_leaves(exact.full_params(pe))):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3)


def test_fsdp_rejects_global_norm_clipping(hvd):
    """Same elementwise precondition (and probe) as ZeRO-1."""
    model = MnistMLP(hidden=32)
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.1))
    with pytest.raises(ValueError, match="ELEMENTWISE"):
        make_fsdp_train_step(_loss_fn(model), opt)


def test_fsdp_shard_params_round_trips(hvd):
    """shard_params (checkpoint restore / broadcast-then-reshard) slices
    identically to init and round-trips through full_params."""
    model = MnistMLP(hidden=24)
    params = init_params(model)
    fstep = make_fsdp_train_step(_loss_fn(model), optax.sgd(0.1),
                                 donate=False)
    p_init, _ = fstep.init(params)
    p_again = fstep.shard_params(fstep.full_params(p_init))
    np.testing.assert_array_equal(np.asarray(p_again), np.asarray(p_init))


def test_fsdp_trainer_integration(hvd):
    """Trainer(fsdp=True): the hot loop runs on the shard while
    trainer.params stays the pytree contract — broadcast callback, LR
    warmup mutation and checkpoint-style reads all work unchanged."""
    import horovod_tpu.callbacks as callbacks
    from horovod_tpu.frontends.loop import Trainer
    from horovod_tpu.models.mnist import synthetic_mnist

    model = MnistMLP(hidden=32)
    params = init_params(model)
    images, labels = synthetic_mnist(128)

    trainer = Trainer(
        _loss_fn(model), params, optimizer_fn=optax.sgd, lr=0.1,
        fsdp=True,
        callbacks=[
            callbacks.BroadcastGlobalVariablesCallback(0),
            callbacks.LearningRateWarmupCallback(warmup_epochs=1,
                                                 steps_per_epoch=4),
        ])

    def batches(epoch, step):
        return (jnp.asarray(images), jnp.asarray(labels))

    history = trainer.fit(batches, epochs=3, steps_per_epoch=4)
    assert history[-1]["loss"] < history[0]["loss"]
    # params property gathers the full pytree for checkpointing.
    full = trainer.params
    assert (jax.tree_util.tree_structure(full)
            == jax.tree_util.tree_structure(params))
    # post-warmup LR reached the base LR.
    np.testing.assert_allclose(trainer.lr, 0.1, rtol=1e-5)


def test_fsdp_shard_params_rejects_new_structure(hvd):
    """Re-sharding a structurally different pytree would silently
    misalign the sharded optimizer state — must fail loudly."""
    model = MnistMLP(hidden=24)
    params = init_params(model)
    fstep = make_fsdp_train_step(_loss_fn(model), optax.sgd(0.1),
                                 donate=False)
    fstep.init(params)
    reordered = {"zzz_extra": jnp.zeros((3,)), **params}
    with pytest.raises(ValueError, match="structure"):
        fstep.shard_params(reordered)


def test_fsdp_trainer_rejects_zero_and_fsdp(hvd):
    from horovod_tpu.frontends.loop import Trainer

    model = MnistMLP(hidden=16)
    with pytest.raises(ValueError, match="mutually exclusive"):
        Trainer(_loss_fn(model), init_params(model), zero=True, fsdp=True)


def test_fsdp_step_before_init_raises(hvd):
    """The flat layout is captured at init(); stepping first must fail
    loudly, not mis-slice."""
    model = MnistMLP(hidden=16)
    fstep = make_fsdp_train_step(_loss_fn(model), optax.sgd(0.1),
                                 donate=False)
    with pytest.raises(RuntimeError, match="init"):
        fstep.step(jnp.zeros((8,)), None, None)
