"""hvd-pipeline checkpoint half: the background rank-0 writer
(utils/checkpoint.py) — overlap, atomicity under a mid-write kill,
ordering, the elastic commit() integration — plus the persistent
compile cache (HVD_TPU_COMPILE_CACHE_DIR: megakernel manifest +
warm start across a simulated elastic relaunch)."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu
from horovod_tpu import elastic
from horovod_tpu.utils import checkpoint as ck


def _tree():
    return {"w": jnp.arange(8.0), "b": np.arange(4.0, dtype="float32")}


def _slow_write(seconds):
    real = ck._write_bytes

    def write(path, blob):
        time.sleep(seconds)
        real(path, blob)

    return write


# ---------------------------------------------------------------------------
# Background writes
# ---------------------------------------------------------------------------

def test_save_checkpoint_async_roundtrip(hvd, tmp_path):
    path = str(tmp_path / "ckpt.msgpack")
    h = ck.save_checkpoint(path, _tree(), step=7)
    assert bool(h)  # the historical truthy-on-rank-0 contract
    assert h.wait(10.0)
    restored = ck.restore_checkpoint(
        path, {"w": jnp.zeros(8), "b": np.zeros(4, "float32")},
        broadcast=False)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8.0))
    np.testing.assert_array_equal(restored["b"], np.arange(4.0))
    assert ck.resume_epoch(path) == 7


def test_save_latency_excludes_disk(hvd, tmp_path, monkeypatch):
    """The acceptance gate: with a deliberately slow filesystem the
    training loop's save latency is the device→host snapshot, not the
    write — the disk time lands on the writer thread."""
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.5))
    path = str(tmp_path / "slow.msgpack")
    t0 = time.perf_counter()
    h = ck.save_checkpoint(path, _tree())
    call_latency = time.perf_counter() - t0
    assert call_latency < 0.25, (
        f"save_checkpoint blocked {call_latency:.3f}s on a 0.5s disk")
    assert not h.done
    assert h.wait(10.0)
    assert os.path.exists(path)
    snap = horovod_tpu.metrics()
    assert snap["checkpoint.write_seconds"]["count"] >= 1
    assert snap["checkpoint.write_seconds"]["sum"] >= 0.5


def test_block_true_restores_sync_semantics(hvd, tmp_path):
    path = str(tmp_path / "sync.msgpack")
    h = ck.save_checkpoint(path, _tree(), block=True)
    assert h.done and os.path.exists(path)


def test_writer_killed_mid_write_previous_checkpoint_intact(
        hvd, tmp_path, monkeypatch):
    """A write that dies midway (partial tmp, no rename) must leave the
    previous checkpoint bytes untouched — restore_checkpoint can never
    see a torn file — and surface the failure at wait()."""
    path = str(tmp_path / "atomic.msgpack")
    ck.save_checkpoint(path, {"v": jnp.asarray(1.0)}).wait(10.0)
    good = open(path, "rb").read()

    def dying_write(p, blob):
        with open(f"{p}.tmp.partial", "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn tmp left behind
        raise OSError("disk died mid-write")

    errors_before = horovod_tpu.metrics().get(
        "checkpoint.errors", {}).get("value", 0)
    monkeypatch.setattr(ck, "_write_bytes", dying_write)
    h = ck.save_checkpoint(path, {"v": jnp.asarray(2.0)})
    with pytest.raises(ck.CheckpointError, match="disk died"):
        h.wait(10.0)
    monkeypatch.undo()
    # The published path still holds the previous checkpoint, bit for bit.
    assert open(path, "rb").read() == good
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 1.0
    assert horovod_tpu.metrics()["checkpoint.errors"]["value"] \
        == errors_before + 1


def test_writes_apply_in_submission_order(hvd, tmp_path):
    path = str(tmp_path / "ordered.msgpack")
    handles = [ck.save_checkpoint(path, {"v": jnp.asarray(float(i))})
               for i in range(5)]
    for h in handles:
        h.wait(10.0)
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 4.0


def test_restore_fences_pending_writes(hvd, tmp_path, monkeypatch):
    """restore right after an async save sees the new bytes (wait_for_
    writes inside restore_checkpoint), even on a slow filesystem."""
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.3))
    path = str(tmp_path / "fence.msgpack")
    ck.save_checkpoint(path, {"v": jnp.asarray(3.0)})
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 3.0


def test_numpy_leaves_snapshot_at_call_time(hvd, tmp_path):
    """In-place mutation after save_checkpoint returns must not leak
    into the written bytes (the writer serializes a snapshot)."""
    arr = np.arange(4.0, dtype="float32")
    path = str(tmp_path / "snap.msgpack")
    h = ck.save_checkpoint(path, {"a": arr})
    arr[:] = -1.0
    h.wait(10.0)
    restored = ck.restore_checkpoint(path, {"a": np.zeros(4, "float32")},
                                     broadcast=False)
    np.testing.assert_array_equal(restored["a"], np.arange(4.0))


def test_pending_gauge_and_wait_for_writes(hvd, tmp_path, monkeypatch):
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.2))
    path = str(tmp_path / "pending.msgpack")
    ck.save_checkpoint(path, _tree())
    assert ck.pending_writes() >= 1
    assert ck.wait_for_writes(10.0)
    assert ck.pending_writes() == 0
    assert horovod_tpu.metrics()["checkpoint.pending"]["value"] == 0


# ---------------------------------------------------------------------------
# Elastic commit() rides the background writer
# ---------------------------------------------------------------------------

def test_elastic_commit_overlaps_disk(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.4))
    state = elastic.State(w=jnp.arange(4.0), step=3)
    t0 = time.perf_counter()
    state.commit()
    commit_latency = time.perf_counter() - t0
    assert commit_latency < 0.2, (
        f"commit blocked {commit_latency:.3f}s on a 0.4s disk")
    assert state.wait_committed(10.0)
    assert os.path.exists(str(tmp_path / elastic._STATE_FILE))


def test_elastic_relaunch_resumes_from_async_commit(hvd, tmp_path,
                                                    monkeypatch):
    """Commit asynchronously, then a fresh State (the relaunched
    incarnation) sync()s: it must converge on the committed values —
    sync fences the in-flight publish first."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.3))
    first = elastic.State(w=jnp.arange(4.0) * 2.0, step=9)
    first.commit()  # returns before the 0.3s write lands

    relaunched = elastic.State(w=jnp.zeros(4), step=0)
    relaunched.sync()
    assert relaunched.step == 9
    np.testing.assert_array_equal(np.asarray(relaunched.w),
                                  np.arange(4.0) * 2.0)


# ---------------------------------------------------------------------------
# Persistent compile cache (HVD_TPU_COMPILE_CACHE_DIR)
# ---------------------------------------------------------------------------

def _fused_cycle(hvd, tag):
    xs = [hvd.shard(np.arange(8 * 4, dtype=np.float32).reshape(8, 4) + i)
          for i in range(3)]
    hs = [hvd.allreduce_async(x, average=True, name=f"{tag}.{i}")
          for i, x in enumerate(xs)]
    return [np.asarray(hvd.synchronize(h)) for h in hs]


def test_compile_cache_reuse_across_simulated_relaunch(tmp_path,
                                                       monkeypatch):
    """First incarnation: a fused allreduce builds a megakernel and
    records it in the manifest.  Simulated relaunch (executables
    flushed, re-init): warm_start AOT-rebuilds the executable at init —
    before any collective runs — and it serves the replayed cycle with
    identical results.  jax's persistent compilation cache is pointed
    at the same directory."""
    from horovod_tpu.ops import megakernel as mk

    cache_dir = str(tmp_path / "compile-cache")
    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", cache_dir)
    import horovod_tpu as hvd

    hvd.init(devices=jax.devices())
    try:
        res1 = _fused_cycle(hvd, "cc")
        manifest = mk.load_manifest(cache_dir)
        assert len(manifest) >= 1
        assert manifest[0]["variant"] in ("sp_pr", "sp_rep")
        assert jax.config.jax_compilation_cache_dir == cache_dir
    finally:
        hvd.shutdown()

    mk.flush("test: simulated relaunch")
    assert mk.cache_size() == 0
    warm_before = mk.stats.warm_starts
    hvd.init(devices=jax.devices())
    try:
        # Warmed at init: executables exist BEFORE the first collective.
        assert mk.cache_size() >= 1
        assert mk.stats.warm_starts > warm_before
        res2 = _fused_cycle(hvd, "cc")
        assert all(a.tobytes() == b.tobytes()
                   for a, b in zip(res1, res2))
        assert horovod_tpu.metrics()[
            "megakernel.warm_starts"]["value"] > 0
    finally:
        hvd.shutdown()


def test_compile_cache_manifest_ignores_foreign_mesh(tmp_path,
                                                     monkeypatch):
    """Entries recorded for a different mesh fingerprint are skipped,
    not compiled against the wrong topology."""
    from horovod_tpu.ops import megakernel as mk

    cache_dir = str(tmp_path / "foreign")
    os.makedirs(cache_dir)
    import json

    with open(os.path.join(cache_dir, mk.MANIFEST_NAME), "w") as f:
        json.dump({"format": "hvd-megakernel-manifest-v1",
                   "entries": [{
                       "variant": "sp_pr", "op": "psum", "average": True,
                       "denom": 4096, "dtype": "float32",
                       "shapes": [[4]], "donate": [True], "hier": False,
                       "digest": None,
                       "mesh": {"platform": "tpu", "device_kind": "v9",
                                "count": 4096}}]}, f)
    import horovod_tpu as hvd

    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", cache_dir)
    hvd.init(devices=jax.devices())
    try:
        assert mk.warm_start(horovod_tpu.mesh(), cache_dir) == 0
    finally:
        hvd.shutdown()


def test_compression_state_rides_checkpoints(hvd, tmp_path, monkeypatch):
    """Quantized-allreduce error-feedback residuals are
    checkpoint-restorable: hvd.compression_state() serializes through
    the normal save/restore path, and after load_compression_state()
    the resumed step replays BITWISE (the EF chain continues instead of
    restarting)."""
    from horovod_tpu.ops import megakernel as mk

    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = horovod_tpu.size()
    rng = np.random.default_rng(21)
    x = horovod_tpu.shard(rng.standard_normal((n, 48)).astype("float32"))
    np.asarray(horovod_tpu.allreduce(x, average=False, name="ckq"))
    snap = horovod_tpu.compression_state()
    assert snap["residuals"]
    path = str(tmp_path / "q.msgpack")
    ck.save_checkpoint(path, {"params": _tree(), "quant": snap},
                       block=True)
    out_next = np.asarray(horovod_tpu.allreduce(x, average=False,
                                                name="ckq"))

    # Simulated relaunch: executor state gone, checkpoint restores it
    # (flax restores by target structure — a snapshot with the same
    # groups serves as the template, exactly as a resumed trainer's
    # would).
    mk.flush("test: relaunch")
    restored = ck.restore_checkpoint(
        path, {"params": _tree(), "quant": snap}, broadcast=False)
    horovod_tpu.load_compression_state(restored["quant"])
    out_resumed = np.asarray(horovod_tpu.allreduce(x, average=False,
                                                   name="ckq"))
    assert out_next.tobytes() == out_resumed.tobytes()
