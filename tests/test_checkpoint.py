"""hvd-pipeline checkpoint half: the background rank-0 writer
(utils/checkpoint.py) — overlap, atomicity under a mid-write kill,
ordering, the elastic commit() integration — plus the persistent
compile cache (HVD_TPU_COMPILE_CACHE_DIR: megakernel manifest +
warm start across a simulated elastic relaunch)."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu
from horovod_tpu import elastic
from horovod_tpu.utils import checkpoint as ck


def _tree():
    return {"w": jnp.arange(8.0), "b": np.arange(4.0, dtype="float32")}


def _slow_write(seconds):
    real = ck._write_bytes

    def write(path, blob):
        time.sleep(seconds)
        real(path, blob)

    return write


# ---------------------------------------------------------------------------
# Background writes
# ---------------------------------------------------------------------------

def test_save_checkpoint_async_roundtrip(hvd, tmp_path):
    path = str(tmp_path / "ckpt.msgpack")
    h = ck.save_checkpoint(path, _tree(), step=7)
    assert bool(h)  # the historical truthy-on-rank-0 contract
    assert h.wait(10.0)
    restored = ck.restore_checkpoint(
        path, {"w": jnp.zeros(8), "b": np.zeros(4, "float32")},
        broadcast=False)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8.0))
    np.testing.assert_array_equal(restored["b"], np.arange(4.0))
    assert ck.resume_epoch(path) == 7


def test_save_latency_excludes_disk(hvd, tmp_path, monkeypatch):
    """The acceptance gate: with a deliberately slow filesystem the
    training loop's save latency is the device→host snapshot, not the
    write — the disk time lands on the writer thread."""
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.5))
    path = str(tmp_path / "slow.msgpack")
    t0 = time.perf_counter()
    h = ck.save_checkpoint(path, _tree())
    call_latency = time.perf_counter() - t0
    assert call_latency < 0.25, (
        f"save_checkpoint blocked {call_latency:.3f}s on a 0.5s disk")
    assert not h.done
    assert h.wait(10.0)
    assert os.path.exists(path)
    snap = horovod_tpu.metrics()
    assert snap["checkpoint.write_seconds"]["count"] >= 1
    assert snap["checkpoint.write_seconds"]["sum"] >= 0.5


def test_block_true_restores_sync_semantics(hvd, tmp_path):
    path = str(tmp_path / "sync.msgpack")
    h = ck.save_checkpoint(path, _tree(), block=True)
    assert h.done and os.path.exists(path)


def test_writer_killed_mid_write_previous_checkpoint_intact(
        hvd, tmp_path, monkeypatch):
    """A write that dies midway (partial tmp, no rename) must leave the
    previous checkpoint bytes untouched — restore_checkpoint can never
    see a torn file — and surface the failure at wait()."""
    path = str(tmp_path / "atomic.msgpack")
    ck.save_checkpoint(path, {"v": jnp.asarray(1.0)}).wait(10.0)
    good = open(path, "rb").read()

    def dying_write(p, blob):
        with open(f"{p}.tmp.partial", "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn tmp left behind
        raise OSError("disk died mid-write")

    errors_before = horovod_tpu.metrics().get(
        "checkpoint.errors", {}).get("value", 0)
    monkeypatch.setattr(ck, "_write_bytes", dying_write)
    h = ck.save_checkpoint(path, {"v": jnp.asarray(2.0)})
    with pytest.raises(ck.CheckpointError, match="disk died"):
        h.wait(10.0)
    monkeypatch.undo()
    # The published path still holds the previous checkpoint, bit for bit.
    assert open(path, "rb").read() == good
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 1.0
    assert horovod_tpu.metrics()["checkpoint.errors"]["value"] \
        == errors_before + 1


def test_writes_apply_in_submission_order(hvd, tmp_path):
    path = str(tmp_path / "ordered.msgpack")
    handles = [ck.save_checkpoint(path, {"v": jnp.asarray(float(i))})
               for i in range(5)]
    for h in handles:
        h.wait(10.0)
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 4.0


def test_restore_fences_pending_writes(hvd, tmp_path, monkeypatch):
    """restore right after an async save sees the new bytes (wait_for_
    writes inside restore_checkpoint), even on a slow filesystem."""
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.3))
    path = str(tmp_path / "fence.msgpack")
    ck.save_checkpoint(path, {"v": jnp.asarray(3.0)})
    restored = ck.restore_checkpoint(path, {"v": jnp.zeros(())},
                                     broadcast=False)
    assert float(restored["v"]) == 3.0


def test_numpy_leaves_snapshot_at_call_time(hvd, tmp_path):
    """In-place mutation after save_checkpoint returns must not leak
    into the written bytes (the writer serializes a snapshot)."""
    arr = np.arange(4.0, dtype="float32")
    path = str(tmp_path / "snap.msgpack")
    h = ck.save_checkpoint(path, {"a": arr})
    arr[:] = -1.0
    h.wait(10.0)
    restored = ck.restore_checkpoint(path, {"a": np.zeros(4, "float32")},
                                     broadcast=False)
    np.testing.assert_array_equal(restored["a"], np.arange(4.0))


def test_pending_gauge_and_wait_for_writes(hvd, tmp_path, monkeypatch):
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.2))
    path = str(tmp_path / "pending.msgpack")
    ck.save_checkpoint(path, _tree())
    assert ck.pending_writes() >= 1
    assert ck.wait_for_writes(10.0)
    assert ck.pending_writes() == 0
    assert horovod_tpu.metrics()["checkpoint.pending"]["value"] == 0


# ---------------------------------------------------------------------------
# Elastic commit() rides the background writer
# ---------------------------------------------------------------------------

def test_elastic_commit_overlaps_disk(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.4))
    state = elastic.State(w=jnp.arange(4.0), step=3)
    t0 = time.perf_counter()
    state.commit()
    commit_latency = time.perf_counter() - t0
    assert commit_latency < 0.2, (
        f"commit blocked {commit_latency:.3f}s on a 0.4s disk")
    assert state.wait_committed(10.0)
    assert os.path.exists(str(tmp_path / elastic._STATE_FILE))


def test_elastic_relaunch_resumes_from_async_commit(hvd, tmp_path,
                                                    monkeypatch):
    """Commit asynchronously, then a fresh State (the relaunched
    incarnation) sync()s: it must converge on the committed values —
    sync fences the in-flight publish first."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setattr(ck, "_write_bytes", _slow_write(0.3))
    first = elastic.State(w=jnp.arange(4.0) * 2.0, step=9)
    first.commit()  # returns before the 0.3s write lands

    relaunched = elastic.State(w=jnp.zeros(4), step=0)
    relaunched.sync()
    assert relaunched.step == 9
    np.testing.assert_array_equal(np.asarray(relaunched.w),
                                  np.arange(4.0) * 2.0)


# ---------------------------------------------------------------------------
# Persistent compile cache (HVD_TPU_COMPILE_CACHE_DIR)
# ---------------------------------------------------------------------------

def _fused_cycle(hvd, tag):
    xs = [hvd.shard(np.arange(8 * 4, dtype=np.float32).reshape(8, 4) + i)
          for i in range(3)]
    hs = [hvd.allreduce_async(x, average=True, name=f"{tag}.{i}")
          for i, x in enumerate(xs)]
    return [np.asarray(hvd.synchronize(h)) for h in hs]


def test_compile_cache_reuse_across_simulated_relaunch(tmp_path,
                                                       monkeypatch):
    """First incarnation: a fused allreduce builds a megakernel and
    records it in the manifest.  Simulated relaunch (executables
    flushed, re-init): warm_start AOT-rebuilds the executable at init —
    before any collective runs — and it serves the replayed cycle with
    identical results.  jax's persistent compilation cache is pointed
    at the same directory."""
    from horovod_tpu.ops import megakernel as mk

    cache_dir = str(tmp_path / "compile-cache")
    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", cache_dir)
    import horovod_tpu as hvd

    hvd.init(devices=jax.devices())
    try:
        res1 = _fused_cycle(hvd, "cc")
        manifest = mk.load_manifest(cache_dir)
        assert len(manifest) >= 1
        assert manifest[0]["variant"] in ("sp_pr", "sp_rep")
        assert jax.config.jax_compilation_cache_dir == cache_dir
    finally:
        hvd.shutdown()

    mk.flush("test: simulated relaunch")
    assert mk.cache_size() == 0
    warm_before = mk.stats.warm_starts
    hvd.init(devices=jax.devices())
    try:
        # Warmed at init: executables exist BEFORE the first collective.
        assert mk.cache_size() >= 1
        assert mk.stats.warm_starts > warm_before
        res2 = _fused_cycle(hvd, "cc")
        assert all(a.tobytes() == b.tobytes()
                   for a, b in zip(res1, res2))
        assert horovod_tpu.metrics()[
            "megakernel.warm_starts"]["value"] > 0
    finally:
        hvd.shutdown()


def test_compile_cache_manifest_ignores_foreign_mesh(tmp_path,
                                                     monkeypatch):
    """Entries recorded for a different mesh fingerprint are skipped,
    not compiled against the wrong topology."""
    from horovod_tpu.ops import megakernel as mk

    cache_dir = str(tmp_path / "foreign")
    os.makedirs(cache_dir)
    import json

    with open(os.path.join(cache_dir, mk.MANIFEST_NAME), "w") as f:
        json.dump({"format": "hvd-megakernel-manifest-v1",
                   "entries": [{
                       "variant": "sp_pr", "op": "psum", "average": True,
                       "denom": 4096, "dtype": "float32",
                       "shapes": [[4]], "donate": [True], "hier": False,
                       "digest": None,
                       "mesh": {"platform": "tpu", "device_kind": "v9",
                                "count": 4096}}]}, f)
    import horovod_tpu as hvd

    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", cache_dir)
    hvd.init(devices=jax.devices())
    try:
        assert mk.warm_start(horovod_tpu.mesh(), cache_dir) == 0
    finally:
        hvd.shutdown()


def test_compression_state_rides_checkpoints(hvd, tmp_path, monkeypatch):
    """Quantized-allreduce error-feedback residuals are
    checkpoint-restorable: hvd.compression_state() serializes through
    the normal save/restore path, and after load_compression_state()
    the resumed step replays BITWISE (the EF chain continues instead of
    restarting)."""
    from horovod_tpu.ops import megakernel as mk

    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    n = horovod_tpu.size()
    rng = np.random.default_rng(21)
    x = horovod_tpu.shard(rng.standard_normal((n, 48)).astype("float32"))
    np.asarray(horovod_tpu.allreduce(x, average=False, name="ckq"))
    snap = horovod_tpu.compression_state()
    assert snap["residuals"]
    path = str(tmp_path / "q.msgpack")
    ck.save_checkpoint(path, {"params": _tree(), "quant": snap},
                       block=True)
    out_next = np.asarray(horovod_tpu.allreduce(x, average=False,
                                                name="ckq"))

    # Simulated relaunch: executor state gone, checkpoint restores it
    # (flax restores by target structure — a snapshot with the same
    # groups serves as the template, exactly as a resumed trainer's
    # would).
    mk.flush("test: relaunch")
    restored = ck.restore_checkpoint(
        path, {"params": _tree(), "quant": snap}, broadcast=False)
    horovod_tpu.load_compression_state(restored["quant"])
    out_resumed = np.asarray(horovod_tpu.allreduce(x, average=False,
                                                   name="ckq"))
    assert out_next.tobytes() == out_resumed.tobytes()


# ---------------------------------------------------------------------------
# Sharded distributed checkpointing (docs/performance.md "Scale-out
# control plane")
# ---------------------------------------------------------------------------

def _big_tree():
    rng = np.random.default_rng(11)
    return {
        "layers": [
            {"w": rng.standard_normal((16, 16)).astype("float32"),
             "b": rng.standard_normal((16,)).astype("float32")}
            for _ in range(3)
        ],
        "head": rng.standard_normal((16, 4)).astype("float64"),
        "meta": {"epoch": 9, "name": "m"},
    }


def _zeros_like_big():
    return {
        "layers": [
            {"w": np.zeros((16, 16), "float32"),
             "b": np.zeros((16,), "float32")}
            for _ in range(3)
        ],
        "head": np.zeros((16, 4), "float64"),
        "meta": {"epoch": 0, "name": ""},
    }


def _assert_trees_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
        else:
            assert x == y


def test_shard_assignment_deterministic_and_balanced():
    sizes = [100, 90, 80, 10, 10, 10, 5, 0]
    a1 = ck.shard_assignment(sizes, 3)
    a2 = ck.shard_assignment(sizes, 3)
    assert a1 == a2
    load = [0, 0, 0]
    for i, w in enumerate(a1):
        load[w] += sizes[i]
    assert max(load) - min(load) <= max(sizes)
    # every writer gets used when there is enough work
    assert set(a1) == {0, 1, 2}


def test_sharded_save_restore_reshards_across_world_sizes(tmp_path):
    """The tentpole gate: save under one world size, restore under
    different ones, parameters bitwise-equal — no broadcast, no rank-0
    byte funnel."""
    d = str(tmp_path / "sharded")
    tree_in = _big_tree()
    h = ck.save_checkpoint_sharded(d, tree_in, step=2, world=2,
                                   block=True)
    assert bool(h) and h.done
    man = ck.load_sharded_manifest(d)
    assert man["world"] == 2 and man["format"] == ck.SHARDED_FORMAT
    # shard files exist for both writer ranks of the declared layout
    sd = os.path.join(d, man["save_dir"])
    assert sorted(f for f in os.listdir(sd) if f.endswith(".msgpack")) \
        == ["shard-00000-of-00002.msgpack", "shard-00001-of-00002.msgpack"]
    # restore "at np=1" and "at np=4" (the layout is irrelevant at
    # restore: every process reads what it needs from shared storage)
    out1 = ck.restore_checkpoint_sharded(d, _zeros_like_big())
    _assert_trees_bitwise(out1, tree_in)
    ck.save_checkpoint_sharded(d, tree_in, step=3, world=4, block=True)
    out4 = ck.restore_checkpoint_sharded(d, _zeros_like_big())
    _assert_trees_bitwise(out4, tree_in)


def test_sharded_torn_fleet_keeps_previous_checkpoint(tmp_path,
                                                      monkeypatch):
    """Mid-write kill of any single host: the manifest commit waits for
    every shard sidecar, times out, and the MANIFEST pointer still
    names the previous COMPLETE save — a partial save can never shadow
    it."""
    d = str(tmp_path / "torn")
    good = _big_tree()
    ck.save_checkpoint_sharded(d, good, step=1, world=2, block=True)
    # Second save at world=2, but only "rank 0" of the fleet survives
    # (virtual=False: strict per-rank shard writing; rank 1 never runs)
    monkeypatch.setenv("HVD_TPU_CKPT_MANIFEST_TIMEOUT", "0.4")
    bad = jax.tree_util.tree_map(
        lambda x: x * 2 if isinstance(x, np.ndarray) else x, good)
    h = ck.save_checkpoint_sharded(d, bad, step=2, world=2, rank=0,
                                   virtual=False)
    with pytest.raises(ck.CheckpointError, match="never became durable"):
        h.wait(30.0)
    man = ck.load_sharded_manifest(d)
    assert man["step"] == 1  # pointer still the previous complete save
    out = ck.restore_checkpoint_sharded(d, _zeros_like_big())
    _assert_trees_bitwise(out, good)


def test_sharded_two_rank_fleet_commit_order(tmp_path):
    """np=2-style save driven rank by rank (strict mode): rank 0's
    manifest commit only lands after rank 1's shard is durable — the
    rank-0-committed-manifest contract without any collective."""
    d = str(tmp_path / "fleet2")
    tree_in = _big_tree()
    # rank 1 writes its shard first, then rank 0 commits
    h1 = ck.save_checkpoint_sharded(d, tree_in, step=5, world=2, rank=1,
                                    virtual=False, block=True)
    assert bool(h1)
    assert not os.path.exists(os.path.join(d, "MANIFEST"))
    h0 = ck.save_checkpoint_sharded(d, tree_in, step=5, world=2, rank=0,
                                    virtual=False, block=True)
    assert bool(h0)
    out = ck.restore_checkpoint_sharded(d, _zeros_like_big())
    _assert_trees_bitwise(out, tree_in)
    assert ck.load_sharded_manifest(d)["shard_digests"].keys() == {"0",
                                                                   "1"}


def test_sharded_restore_rejects_corrupt_shard(tmp_path):
    d = str(tmp_path / "corrupt")
    ck.save_checkpoint_sharded(d, _big_tree(), step=1, world=2,
                               block=True)
    man = ck.load_sharded_manifest(d)
    victim = os.path.join(d, man["save_dir"],
                          "shard-00001-of-00002.msgpack")
    with open(victim, "r+b") as f:
        f.seek(0)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(ck.CheckpointError, match="digest mismatch"):
        ck.restore_checkpoint_sharded(d, _zeros_like_big())


def test_restore_broadcast_skip_decision(monkeypatch):
    """The broadcast-elision rule: skip only when EVERY rank gathered
    the same non-None digest (checkpoint.broadcast_skipped counts it);
    any missing or divergent local file falls back to the classic
    rank-0 broadcast."""
    calls = {}

    def fake_allgather(obj, name=None):
        calls["digest"] = obj
        return calls["fleet"]

    monkeypatch.setattr("horovod_tpu.ops.objects.allgather_object",
                        fake_allgather)
    calls["fleet"] = ["d1", "d1", "d1"]
    assert ck._broadcast_skippable("d1")
    calls["fleet"] = ["d1", "d2", "d1"]
    assert not ck._broadcast_skippable("d1")
    calls["fleet"] = ["d1", None, "d1"]
    assert not ck._broadcast_skippable("d1")
    calls["fleet"] = []
    assert not ck._broadcast_skippable(None)


def test_sharded_untagged_save_requires_step_in_mp(tmp_path):
    """The tag must be fleet-agreed: an untagged save in strict
    multi-rank mode is a contract error (a process-local counter
    diverges across elastic restarts)."""
    with pytest.raises(ValueError, match="requires step="):
        ck.save_checkpoint_sharded(str(tmp_path / "x"), _big_tree(),
                                   world=2, rank=0, virtual=False)


def test_sharded_retry_ignores_stale_sidecars_from_torn_attempt(
        tmp_path, monkeypatch):
    """Torn-retry freshness: a save-<tag>/ left by a torn attempt (no
    committed manifest) holds self-consistent shard+.ok pairs; a retry
    under the same tag must NOT let the commit consume them until the
    owning rank republishes — otherwise the manifest could mix
    attempts (or record a digest mid-rewrite)."""
    d = str(tmp_path / "retry")
    tree_a = _big_tree()
    # attempt 1, torn: rank 1 published, rank 0 (the committer) died
    ck.save_checkpoint_sharded(d, tree_a, step=7, world=2, rank=1,
                               virtual=False, block=True)
    assert not os.path.exists(os.path.join(d, "MANIFEST"))
    # age the leftover sidecar past the staleness margin (a real torn
    # retry happens after a job restart, minutes later)
    stale_ok = os.path.join(d, "save-s7",
                            "shard-00001-of-00002.msgpack.ok")
    past = time.time() - 3600
    os.utime(stale_ok, (past, past))
    # attempt 2 with DIFFERENT bytes: rank 0 runs, rank 1 never
    # republishes -> the stale sidecar must not satisfy the commit
    monkeypatch.setenv("HVD_TPU_CKPT_MANIFEST_TIMEOUT", "0.6")
    tree_b = jax.tree_util.tree_map(
        lambda x: x + 1 if isinstance(x, np.ndarray) else x, tree_a)
    h = ck.save_checkpoint_sharded(d, tree_b, step=7, world=2, rank=0,
                                   virtual=False)
    with pytest.raises(ck.CheckpointError, match="never became durable"):
        h.wait(30.0)
    assert not os.path.exists(os.path.join(d, "MANIFEST"))
