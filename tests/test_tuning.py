"""hvd-tune (ISSUE 18): the closed-loop online self-tuning subsystem.

Policy-engine unit tests run the pure rule table over seeded
WindowSnapshot sequences (no runtime, no clock): a rule fires exactly
once per sustained diagnosis, boundary-flapping input never accumulates
the hysteresis streak, and a planner veto is counted while the knob
stays untouched.  The actuation tests drive REAL eager traffic through
init so RETUNE markers ride the production response stream; the np=2
coherence leg runs a real controller+worker transport pair over
loopback and asserts both ranks log the same decision sequence at the
same stream positions.
"""

import os
import re
import socket
import threading
import time
from types import SimpleNamespace

import jax.numpy as jnp
import pytest

from horovod_tpu.tuning import policy as tuning_policy
from horovod_tpu.tuning.policy import (COMPRESSION_LADDER,
                                       KNOB_DCN_COMPRESS,
                                       KNOB_FUSION_THRESHOLD,
                                       KNOB_MAX_INFLIGHT,
                                       KNOB_PREFIX_PAGES,
                                       KNOB_SPEC_TOKENS, PolicyConfig,
                                       PolicyEngine, WindowSnapshot)

THRESHOLD = 1 << 20

DEFAULT_KNOBS = {
    KNOB_DCN_COMPRESS: "none",
    KNOB_MAX_INFLIGHT: 2,
    KNOB_FUSION_THRESHOLD: 64 << 20,
    "cycle_time": 0.005,
    KNOB_SPEC_TOKENS: 3,
}

FLAT_LEGS = {"host": 100.0, "collective": 100.0, "dcn": 10.0,
             "dispatch": 100.0, "dispatch-gap": 10.0}
DCN_LEGS = {"host": 50.0, "collective": 50.0, "dcn": 400.0,
            "dispatch": 50.0, "dispatch-gap": 10.0}
GAP_LEGS = {"host": 50.0, "collective": 50.0, "dcn": 10.0,
            "dispatch": 50.0, "dispatch-gap": 400.0}


def snap(index, legs=FLAT_LEGS, knobs=None, **kw):
    return WindowSnapshot(index=index, legs=dict(legs),
                          knobs=dict(knobs or DEFAULT_KNOBS), **kw)


# ---------------------------------------------------------------------------
# Policy engine: seeded-snapshot unit tests
# ---------------------------------------------------------------------------

def test_dcn_rule_fires_exactly_once_per_sustained_diagnosis():
    """sustain=2: window 0 arms the streak, window 1 fires ONE ladder
    escalation, window 2 is silenced by the post-fire streak reset and
    the knob cooldown — one decision per sustained diagnosis, not one
    per window the condition holds."""
    eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=2))
    assert eng.step(snap(0, DCN_LEGS)) is None
    d = eng.step(snap(1, DCN_LEGS))
    assert d is not None
    assert (d.knob, d.value) == (KNOB_DCN_COMPRESS, "bf16")
    assert d.wire() == "dcn_compress=bf16"
    assert eng.step(snap(2, DCN_LEGS)) is None
    assert len(eng.decisions) == 1


def test_dcn_ladder_climbs_one_rung_per_decision():
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    values = []
    knobs = dict(DEFAULT_KNOBS)
    for i in range(6):
        d = eng.step(snap(i, DCN_LEGS, knobs))
        if d is not None:
            values.append(d.value)
            knobs[KNOB_DCN_COMPRESS] = d.value  # the fleet applied it
    # Climbs none -> bf16 -> int8 -> int4 and then stops at the floor.
    assert values == list(COMPRESSION_LADDER[1:])


def test_hysteresis_boundary_flapping_never_fires():
    """A condition alternating true/false each window never reaches the
    sustain streak — the anti-thrash contract."""
    eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=2))
    for i in range(12):
        legs = DCN_LEGS if i % 2 == 0 else FLAT_LEGS
        assert eng.step(snap(i, legs)) is None
    assert eng.decisions == []
    assert eng.vetoes == 0


def test_planner_veto_counts_and_leaves_knob_untouched():
    """A candidate whose priced byte delta exceeds the window's known
    headroom is vetoed: counted, logged, no decision, and the knob is
    cooled down so the doomed candidate is not re-priced every window."""
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=3),
                       price=lambda knob, old, new, s: 10 << 30)
    s = snap(0, GAP_LEGS, headroom_frac=0.5, headroom_bytes=1 << 20)
    assert eng.step(s) is None
    assert eng.vetoes == 1
    assert eng.decisions == []
    assert eng.veto_log[0][1] == KNOB_MAX_INFLIGHT
    # Cooldown active: the next windows don't even re-price.
    assert eng.step(snap(1, GAP_LEGS, headroom_frac=0.5,
                         headroom_bytes=1 << 20)) is None
    assert eng.vetoes == 1


def test_cheap_candidate_passes_the_priced_veto():
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0),
                       price=lambda knob, old, new, s: 64)
    d = eng.step(snap(0, GAP_LEGS, headroom_frac=0.5,
                      headroom_bytes=1 << 20))
    assert d is not None and d.knob == KNOB_MAX_INFLIGHT
    assert d.value == 4  # widen 2 -> 4
    assert eng.vetoes == 0


def test_straggler_rule_rebuckets_after_persistence():
    """The straggler rule's hysteresis is its same-rank streak: two
    consecutive windows blaming rank 1 fire one fusion re-bucket."""
    eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=2))
    assert eng.step(snap(0, straggler_rank=1)) is None
    d = eng.step(snap(1, straggler_rank=1))
    assert d is not None
    assert d.knob == KNOB_FUSION_THRESHOLD
    assert d.value == (64 << 20) // 2
    assert "rank 1" in d.reason


def test_straggler_rank_change_resets_persistence():
    eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=2))
    assert eng.step(snap(0, straggler_rank=1)) is None
    assert eng.step(snap(1, straggler_rank=2)) is None  # new rank: restart
    assert eng.step(snap(2, straggler_rank=-1)) is None
    assert eng.decisions == []


def test_low_acceptance_shrinks_spec_tokens_to_floor():
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    knobs = dict(DEFAULT_KNOBS)
    values = []
    for i in range(5):
        d = eng.step(snap(i, spec_acceptance=0.2, knobs=knobs))
        if d is not None:
            values.append(d.value)
            knobs[KNOB_SPEC_TOKENS] = d.value
    assert values == [2, 1]  # 3 -> 2 -> 1, then the floor holds


def test_prefix_reserve_grows_under_kv_pressure_with_hot_index():
    """hvd-route satellite: a HOT shared-prefix index (hit rate >= high)
    while KV admission headroom thrashes (kv_free_frac < floor) earns a
    dedicated page reserve, doubling up to the cap."""
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    knobs = dict(DEFAULT_KNOBS)
    values = []
    for i in range(8):
        d = eng.step(snap(i, kv_free_frac=0.1, prefix_hit_rate=0.7,
                          knobs=knobs))
        if d is not None:
            assert d.knob == KNOB_PREFIX_PAGES
            values.append(d.value)
            knobs[KNOB_PREFIX_PAGES] = d.value
    assert values == [8, 16, 32, 64, 128, 256]  # then the cap holds
    assert "grow the prefix reserve" in eng.decisions[0].reason


def test_prefix_reserve_shrinks_when_index_goes_cold():
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    knobs = dict(DEFAULT_KNOBS)
    knobs[KNOB_PREFIX_PAGES] = 32
    d = eng.step(snap(0, prefix_hit_rate=0.01, knobs=knobs))
    assert d is not None
    assert d.knob == KNOB_PREFIX_PAGES
    assert d.value == 16
    assert "shrink the prefix reserve" in d.reason


def test_prefix_rules_idle_in_dead_band_and_without_signal():
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    # Dead band: hit rate between low and high never moves the knob.
    assert eng.step(snap(0, kv_free_frac=0.1,
                         prefix_hit_rate=0.3)) is None
    # Hot index but ample KV headroom: no pressure, no reserve.
    assert eng.step(snap(1, kv_free_frac=0.9,
                         prefix_hit_rate=0.9)) is None
    # Cold index with no reserve: nothing to give back.
    assert eng.step(snap(2, prefix_hit_rate=0.0)) is None
    # Unknown sensors (the -1.0 defaults) hold everything still.
    assert eng.step(snap(3)) is None
    assert eng.decisions == []


def test_prefix_grow_is_planner_priced():
    """The reserve's byte delta rides the same priced veto as every
    other knob — a grow the host cannot afford is refused."""
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=3),
                       price=lambda knob, old, new, s: 10 << 30)
    assert eng.step(snap(0, kv_free_frac=0.1, prefix_hit_rate=0.7,
                         headroom_bytes=1 << 20)) is None
    assert eng.vetoes == 1
    assert eng.veto_log[0][1] == KNOB_PREFIX_PAGES


def test_headroom_pressure_outranks_speed_rules():
    """Safety first: under HBM pressure the byte-saving rule wins even
    when the dcn leg dominates the critical path."""
    eng = PolicyEngine(PolicyConfig(sustain=1, cooldown=0))
    d = eng.step(snap(0, DCN_LEGS, headroom_frac=0.05,
                      headroom_bytes=1 << 20))
    assert d is not None
    assert d.knob == KNOB_FUSION_THRESHOLD  # shrink buffers, not wire
    assert "headroom" in d.reason


def test_pinned_knob_is_never_touched():
    eng = PolicyEngine(PolicyConfig(
        sustain=1, cooldown=0, pinned=frozenset({KNOB_DCN_COMPRESS})))
    for i in range(4):
        assert eng.step(snap(i, DCN_LEGS)) is None
    assert eng.decisions == []


def test_decision_sequence_is_deterministic():
    """Same seeded snapshot sequence through two fresh engines: the
    decision sequences are identical — the replay gate bench.py --mode
    tuning enforces end to end."""
    feed = ([snap(i, DCN_LEGS) for i in range(3)]
            + [snap(i, GAP_LEGS, straggler_rank=1) for i in range(3, 6)]
            + [snap(i, spec_acceptance=0.1) for i in range(6, 10)])

    def run():
        eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=1))
        for s in feed:
            eng.step(s)
        return [(d.seq, d.window, d.knob, d.value) for d in eng.decisions]

    first = run()
    assert first  # the feed produces decisions
    assert run() == first


# ---------------------------------------------------------------------------
# Pricing + env validation
# ---------------------------------------------------------------------------

def test_retune_delta_bytes_formulas():
    from horovod_tpu.memory.planner import retune_delta_bytes

    knobs = {KNOB_FUSION_THRESHOLD: 4 << 20, "spec_token_bytes": 1024}
    assert retune_delta_bytes(KNOB_FUSION_THRESHOLD, 4 << 20, 8 << 20,
                              knobs) == 2 * (4 << 20)
    assert retune_delta_bytes(KNOB_FUSION_THRESHOLD, 8 << 20, 4 << 20,
                              knobs) == -2 * (4 << 20)
    assert retune_delta_bytes(KNOB_MAX_INFLIGHT, 2, 4,
                              knobs) == 2 * (4 << 20)
    assert retune_delta_bytes(KNOB_SPEC_TOKENS, 3, 2, knobs) == -1024
    # Non-numeric knobs (the compression ladder) price as free.
    assert retune_delta_bytes(KNOB_DCN_COMPRESS, "none", "int8",
                              knobs) == 0


def test_validate_env_rejects_unknown_pin(monkeypatch):
    from horovod_tpu import tuning

    monkeypatch.setenv("HVD_TPU_TUNE_PIN", "dcn_compress,flux_capacitor")
    with pytest.raises(ValueError, match="flux_capacitor"):
        tuning.validate_env()


def test_validate_env_rejects_bad_window(monkeypatch):
    from horovod_tpu import tuning

    monkeypatch.setenv("HVD_TPU_TUNE_WINDOW", "soon")
    with pytest.raises(ValueError, match="HVD_TPU_TUNE_WINDOW"):
        tuning.validate_env()


# ---------------------------------------------------------------------------
# Actuation: markers ride the production response stream
# ---------------------------------------------------------------------------

def _drive_until_applied(hvd, st, seq, deadline_s=20.0):
    deadline = time.monotonic() + deadline_s
    i = 0
    while st.tuner._applied_seq < seq and time.monotonic() < deadline:
        hvd.allreduce(jnp.ones((4,)), name=f"tune.drive.{i}",
                      average=False)
        i += 1
    assert st.tuner._applied_seq >= seq, "marker was never applied"


def test_retune_marker_applies_at_cycle_boundary(monkeypatch, capfd):
    """End to end on the real single-process runtime: an enqueued
    decision rides the next coordinator tick as a RETUNE marker and is
    applied by the response executor — env knob set, megakernels
    flushed, the apply line logged, tuning.applied incremented."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu import telemetry
    from horovod_tpu.core import state as _state

    monkeypatch.setenv("HVD_TPU_TUNE", "1")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "none")
    monkeypatch.setenv("HVD_TPU_MAX_INFLIGHT", "2")
    hvd.init(devices=jax.devices())
    try:
        st = _state.global_state()
        assert st.tuner is not None
        assert st.tuner is st.autotuner
        seq = st.tuner._enqueue(["dcn_compress=int8", "max_inflight=4"])
        _drive_until_applied(hvd, st, seq)
        assert os.environ["HVD_TPU_DCN_COMPRESS"] == "int8"
        assert os.environ["HVD_TPU_MAX_INFLIGHT"] == "4"
        assert telemetry.metrics()["tuning.applied"]["value"] >= 2
        err = capfd.readouterr().err
        assert f"rank 0 applied seq={seq} " \
               f"dcn_compress=int8 max_inflight=4" in err
    finally:
        hvd.shutdown()


def test_malformed_retune_token_is_skipped_with_diagnostic(monkeypatch,
                                                           capfd):
    """A marker carrying garbage must not wedge the drain tick: the bad
    token is skipped with a named diagnostic, the good token applies."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.core import state as _state

    monkeypatch.setenv("HVD_TPU_TUNE", "1")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "none")
    hvd.init(devices=jax.devices())
    try:
        st = _state.global_state()
        before = st.tick_seconds
        seq = st.tuner._enqueue(["dcn_compress=bogus",
                                 "cycle_time=0.004"])
        _drive_until_applied(hvd, st, seq)
        assert os.environ["HVD_TPU_DCN_COMPRESS"] == "none"  # untouched
        assert st.tick_seconds == pytest.approx(0.004)
        assert before != 0.004
        err = capfd.readouterr().err
        assert "skipping malformed retune 'dcn_compress=bogus'" in err
    finally:
        hvd.shutdown()


def test_inflight_window_resize_is_live():
    from horovod_tpu.parallel.overlap import _InflightWindow
    from horovod_tpu.tuning import actuation

    w = _InflightWindow(4)
    assert w in list(actuation._inflight_windows)
    actuation._apply_max_inflight(None, 1)
    assert w._depth == 1
    assert os.environ["HVD_TPU_MAX_INFLIGHT"] == "1"
    os.environ.pop("HVD_TPU_MAX_INFLIGHT", None)


def test_install_is_inert_without_opt_in(monkeypatch):
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.core import state as _state

    monkeypatch.delenv("HVD_TPU_TUNE", raising=False)
    monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
    hvd.init(devices=jax.devices())
    try:
        st = _state.global_state()
        assert st.tuner is None and st.autotuner is None
    finally:
        hvd.shutdown()


# ---------------------------------------------------------------------------
# np=2 decision coherence: both ranks, same sequence, same positions
# ---------------------------------------------------------------------------

APPLY_RE = re.compile(r"\[hvd-tune\] rank (\d+) applied seq=(\d+) (.+)")


def _fake_state(rank, coordinator=None, response_cache=None):
    return SimpleNamespace(process_index=rank, tuner=None,
                           multiprocess=True, transport=None,
                           coordinator=coordinator,
                           response_cache=response_cache,
                           fusion_threshold_bytes=64 << 20,
                           tick_seconds=0.005)


def test_np2_ranks_apply_identical_decision_sequence(monkeypatch, capfd):
    """The fleet-coherence contract over real loopback transports: the
    rank-0 policy's decisions, broadcast as RETUNE markers, are applied
    by BOTH ranks in the same order at the same stream positions — the
    per-rank apply logs carry identical (position, seq, knobs)
    sequences."""
    from horovod_tpu.ops import cache as hvd_cache
    from horovod_tpu.ops import transport as T
    from horovod_tpu.ops.coordinator import Coordinator
    from horovod_tpu.ops.wire import ResponseType
    from horovod_tpu.tuning import actuation

    if os.environ.get("HVD_TPU_NO_SOCKETS") == "1":
        pytest.skip("sandbox without loopback sockets")
    monkeypatch.setenv("HVD_TPU_DCN_COMPRESS", "none")
    monkeypatch.setenv("HVD_TPU_MAX_INFLIGHT", "2")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD,
                        cache=hvd_cache.ResponseCache(rank=0))
    holder = {}
    th = threading.Thread(
        target=lambda: holder.__setitem__(
            "ctrl", T.ControllerTransport(coord, 2, port)),
        daemon=True)
    th.start()
    time.sleep(0.1)
    worker = T.WorkerTransport("127.0.0.1", port, 1)
    th.join(timeout=10.0)
    ctrl = holder["ctrl"]
    st0 = _fake_state(0, coordinator=coord)
    st1 = _fake_state(1, response_cache=hvd_cache.ResponseCache(rank=1))
    try:
        # The REAL rule table drives the decisions: a dcn-dominated
        # window feed, each decision broadcast the moment it fires.
        eng = PolicyEngine(PolicyConfig(sustain=2, cooldown=1))
        knobs = dict(DEFAULT_KNOBS)
        n_sent = 0
        for i in range(8):
            d = eng.step(snap(i, DCN_LEGS, knobs))
            if d is None:
                continue
            knobs[d.knob] = d.value
            marker = actuation.make_marker([d.wire()], d.seq)
            ctrl.broadcast_responses([marker])
            actuation.apply_marker(marker, st0)  # rank 0's executor
            n_sent += 1
        assert n_sent >= 2
        applied = 0
        deadline = time.monotonic() + 10.0
        while applied < n_sent and time.monotonic() < deadline:
            resps = worker.poll_responses()
            if resps is None:
                time.sleep(0.005)
                continue
            for r in resps:
                if r.response_type == ResponseType.RETUNE:
                    actuation.apply_marker(r, st1)  # rank 1's executor
                    applied += 1
        assert applied == n_sent, "worker missed a marker"
        err = capfd.readouterr().err
        by_rank = {0: [], 1: []}
        for line in err.splitlines():
            m = APPLY_RE.match(line.strip())
            if m:
                by_rank[int(m.group(1))].append(
                    (m.group(2), m.group(3)))
        assert len(by_rank[0]) == n_sent
        # Identical (seq, knob=value) sequences at identical positions.
        assert by_rank[0] == by_rank[1]
        # And the env digests agree after the full sequence (the gauge
        # the production controller's fleet verification compares).
        assert actuation.env_digest() == actuation.env_digest()
    finally:
        worker.close()
        ctrl.close()
        coord.close()
