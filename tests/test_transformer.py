"""Transformer LM tests: every parallelism composition must reproduce the
single-device forward, and the combined train step must learn."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core.topology import make_mesh
from horovod_tpu.models.transformer import (ParallelAxes,
                                            TransformerConfig, forward,
                                            init_transformer,
                                            make_loss_fn,
                                            synthetic_lm_batch)
from horovod_tpu.parallel.training import (make_parallel_train_step,
                                           shard_parallel_batch)

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, max_seq_len=128, block_q=16, block_k=16)
TOL = 2e-4


def _data(cfg=CFG, batch=8, seq=32, seed=0):
    key = jax.random.PRNGKey(seed)
    kp, kd = jax.random.split(key)
    params = init_transformer(kp, cfg)
    tokens, targets = synthetic_lm_batch(kd, batch, seq, cfg.vocab_size)
    return params, tokens, targets


def _single_device_logits(params, tokens, cfg=CFG):
    logits, aux = forward(params, tokens, cfg, ParallelAxes(data=None))
    return logits, aux


@pytest.mark.parametrize("axes_kw,mesh_kw,batch_spec", [
    (dict(data="data"), dict(data=8), P("data", None)),
    (dict(data="data", model="model"), dict(data=2, model=4),
     P("data", None)),
    (dict(data="data", seq="seq"), dict(data=2, seq=4),
     P("data", "seq")),
    (dict(data="data", seq="seq", model="model"),
     dict(data=2, seq=2, model=2), P("data", "seq")),
])
def test_parallel_forward_matches_single_device(axes_kw, mesh_kw,
                                                batch_spec):
    mesh = make_mesh(**mesh_kw)
    ax = ParallelAxes(**axes_kw)
    params, tokens, targets = _data()

    def local(params, tokens):
        logits, aux = forward(params, tokens, CFG, ax)
        return logits

    out_spec = P(ax.data, ax.seq, None)
    got = jax.jit(_compat.shard_map(local, mesh=mesh,
                                in_specs=(P(), batch_spec),
                                out_specs=out_spec,
                                check_vma=False))(params, tokens)
    want, _ = _single_device_logits(params, tokens)
    assert np.max(np.abs(np.asarray(got) - np.asarray(want))) < TOL


def test_pipeline_forward_matches_single_device():
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    ax = ParallelAxes(data="data", pipe="pipe", num_microbatches=2)
    params, tokens, targets = _data()

    def local(params, tokens):
        logits, aux = forward(params, tokens, CFG, ax)
        return logits

    got = jax.jit(_compat.shard_map(local, mesh=mesh,
                                in_specs=(P(), P("data", None)),
                                out_specs=P("data", None, None),
                                check_vma=False))(params, tokens)
    want, _ = _single_device_logits(params, tokens)
    assert np.max(np.abs(np.asarray(got) - np.asarray(want))) < TOL


def test_moe_transformer_runs_and_is_finite():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=128,
                            num_experts=4, top_k=2, capacity_factor=4.0,
                            block_q=16, block_k=16)
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    ax = ParallelAxes(data="data", expert="data")
    params, tokens, targets = _data(cfg)

    loss_fn = make_loss_fn(cfg, ax, mesh_axes=mesh.axis_names)
    sm = _compat.shard_map(loss_fn, mesh=mesh,
                       in_specs=(P(), P("data", None)), out_specs=P(),
                       check_vma=False)
    loss = jax.jit(sm)(params, (tokens, targets))
    assert bool(jnp.isfinite(loss))
    grads = jax.jit(jax.grad(sm))(params, (tokens, targets))
    flat, _ = jax.tree_util.tree_flatten(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
    # Expert + router weights actually receive gradient.
    assert bool(jnp.any(grads["layers"]["router"] != 0))
    assert bool(jnp.any(grads["layers"]["moe_w_in"] != 0))


def test_combined_train_step_learns():
    # dp=2 × sp=2 × tp=2: the full jitted step on an 8-device mesh.
    mesh = make_mesh(data=2, seq=2, model=2)
    ax = ParallelAxes(data="data", seq="seq", model="model")
    params, tokens, targets = _data(batch=8)

    loss_fn = make_loss_fn(CFG, ax, mesh_axes=mesh.axis_names)
    opt = optax.adam(1e-2)
    step = make_parallel_train_step(loss_fn, opt, mesh,
                                    P("data", "seq"), donate=False)
    batch = shard_parallel_batch((tokens, targets), mesh,
                                 P("data", "seq"))
    opt_state = opt.init(params)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1, losses


def test_parallel_gradients_match_single_device():
    mesh = make_mesh(data=2, seq=2, model=2)
    ax = ParallelAxes(data="data", seq="seq", model="model")
    params, tokens, targets = _data(batch=4)

    loss_fn = make_loss_fn(CFG, ax, mesh_axes=mesh.axis_names)
    sm = _compat.shard_map(loss_fn, mesh=mesh,
                       in_specs=(P(), P("data", "seq")), out_specs=P(),
                       check_vma=False)
    got = jax.jit(jax.grad(sm))(params, (tokens, targets))

    single_loss = make_loss_fn(CFG, ParallelAxes(data=None),
                               mesh_axes=())
    want = jax.jit(jax.grad(
        lambda p: single_loss(p, (tokens, targets))))(params)
    flat_got, _ = jax.tree_util.tree_flatten(got)
    flat_want, _ = jax.tree_util.tree_flatten(want)
    for a, b in zip(flat_got, flat_want):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 5e-4


def test_remat_matches_exact_gradients():
    # cfg.remat must change memory/FLOPs only — loss and gradients are
    # bit-compatible with the non-remat trace (same ops, same order).
    import dataclasses

    params, tokens, targets = _data(batch=4)
    base = make_loss_fn(CFG, ParallelAxes(data=None), mesh_axes=())
    remat_cfg = dataclasses.replace(CFG, remat=True)
    rem = make_loss_fn(remat_cfg, ParallelAxes(data=None), mesh_axes=())

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: base(p, (tokens, targets))))(params)
    l1, g1 = jax.jit(jax.value_and_grad(
        lambda p: rem(p, (tokens, targets))))(params)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_chunked_loss_matches_dense():
    # cfg.loss_chunk must change memory only: loss and gradients match
    # the full-logits computation.
    import dataclasses

    params, tokens, targets = _data(batch=4)
    dense = make_loss_fn(CFG, ParallelAxes(data=None), mesh_axes=())
    chunked_cfg = dataclasses.replace(CFG, loss_chunk=8)
    chunked = make_loss_fn(chunked_cfg, ParallelAxes(data=None),
                           mesh_axes=())

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: dense(p, (tokens, targets))))(params)
    l1, g1 = jax.jit(jax.value_and_grad(
        lambda p: chunked(p, (tokens, targets))))(params)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_chunked_loss_composes_with_seq_parallel():
    import dataclasses

    mesh = make_mesh(data=2, seq=4)
    ax = ParallelAxes(data="data", seq="seq")
    cfg = dataclasses.replace(CFG, loss_chunk=4, remat=True)
    params, tokens, targets = _data(batch=4)
    loss_fn = make_loss_fn(cfg, ax, mesh_axes=mesh.axis_names)
    sm = _compat.shard_map(loss_fn, mesh=mesh,
                       in_specs=(P(), P("data", "seq")), out_specs=P(),
                       check_vma=False)
    loss, grads = jax.jit(jax.value_and_grad(sm))(params,
                                                  (tokens, targets))
    single = make_loss_fn(CFG, ParallelAxes(data=None), mesh_axes=())
    want_l, want_g = jax.jit(jax.value_and_grad(
        lambda p: single(p, (tokens, targets))))(params)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(want_l),
                               rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(want_g)):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 5e-4


def test_remat_composes_with_parallel_axes():
    import dataclasses

    mesh = make_mesh(data=2, seq=2, model=2)
    ax = ParallelAxes(data="data", seq="seq", model="model")
    cfg = dataclasses.replace(CFG, remat=True)
    params, tokens, targets = _data(batch=4)
    loss_fn = make_loss_fn(cfg, ax, mesh_axes=mesh.axis_names)
    sm = _compat.shard_map(loss_fn, mesh=mesh,
                       in_specs=(P(), P("data", "seq")), out_specs=P(),
                       check_vma=False)
    loss, grads = jax.jit(jax.value_and_grad(sm))(params,
                                                  (tokens, targets))
    assert np.isfinite(np.asarray(loss))
    # Against the non-remat single-device reference.
    single = make_loss_fn(CFG, ParallelAxes(data=None), mesh_axes=())
    want = jax.jit(jax.grad(lambda p: single(p, (tokens, targets))))(params)
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(want)):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 5e-4


def test_pipeline_rejects_indivisible_layers():
    mesh = make_mesh(pipe=3, devices=jax.devices()[:3])
    ax = ParallelAxes(data=None, pipe="pipe")
    params, tokens, _ = _data()
    sm = _compat.shard_map(
        lambda p, t: forward(p, t, CFG, ax)[0], mesh=mesh,
        in_specs=(P(), P()), out_specs=P(), check_vma=False)
    with pytest.raises(ValueError, match="not divisible"):
        sm(params, tokens)
