"""Pallas flash-attention kernel vs the O(seq²) reference.

Style follows the reference's self-verifying collective tests
(test/test_tensorflow.py:34-63): compute both ways, compare with a float
tolerance.  Runs in Pallas interpreter mode on the CPU test mesh.
"""

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.ops.flash_attention import (flash_attention,
                                             flash_attention_with_lse,
                                             mha_reference)

TOL = 5e-5


@pytest.fixture(autouse=True)
def _force_pallas_interpreter(monkeypatch):
    """These tests verify the Pallas kernels themselves: disable the
    dense-jnp CPU fallback that the rest of the suite rides."""
    monkeypatch.setenv("HVD_TPU_FLASH_INTERPRET", "1")


def _qkv(b=2, h=3, s=128, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [32, 64, 128])
def test_forward_matches_reference(causal, block):
    q, k, v = _qkv()
    o = flash_attention(q, k, v, causal=causal, block_q=block,
                        block_k=block)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(o - ref)) < TOL


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_reference(causal):
    q, k, v = _qkv(s=96, d=16)
    w = jnp.cos(jnp.arange(16))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=32,
                                       block_k=32) * w)

    def g(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * w)

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    want = jax.grad(g, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_uneven_blocks():
    # seq not a multiple of the block size exercises the pad/mask tail.
    q, k, v = _qkv(s=80, d=16)
    o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.max(jnp.abs(o - ref)) < TOL

    w = jnp.cos(jnp.arange(16))
    got = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32) * w),
        (0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) * w),
        (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_cross_attention_q_shorter_than_kv():
    q, _, _ = _qkv(s=32)
    _, k, v = _qkv(s=128, seed=1)
    o = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    assert jnp.max(jnp.abs(o - ref)) < TOL


def test_q_block_offset_matches_shifted_causal_mask():
    # A q shard whose global rows start at 64 (ring-attention layout).
    q, k, v = _qkv(s=128)
    q_shard = q[:, :, 64:96]
    o, lse = flash_attention_with_lse(q_shard, k, v, causal=True,
                                      q_block_offset=64, block_q=32,
                                      block_k=32)
    ref = mha_reference(q_shard, k, v, causal=True, q_block_offset=64)
    assert jnp.max(jnp.abs(o - ref)) < TOL
    assert lse.shape == (2, 3, 32)
    assert bool(jnp.all(jnp.isfinite(lse)))


def test_fully_masked_rows_are_zero_not_nan():
    # q_block_offset placing all queries before every key masks everything.
    q, k, v = _qkv(s=32)
    o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                      q_block_offset=-1000)
    assert bool(jnp.all(o == 0.0))
    assert bool(jnp.all(jnp.isneginf(lse)))


def test_lse_matches_reference_logsumexp():
    q, k, v = _qkv(s=64, d=16)
    _, lse = flash_attention_with_lse(q, k, v, block_q=32, block_k=32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (16 ** -0.5)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    assert jnp.max(jnp.abs(lse - ref_lse)) < TOL


@pytest.mark.parametrize("causal", [False, True])
def test_streaming_kernels_match_reference(causal, monkeypatch):
    # Force the long-seq streaming kernels (3D grid + VMEM scratch,
    # causal DMA-elision index maps) at test-size shapes; short shapes
    # otherwise dispatch to the resident kernels.
    monkeypatch.setenv("HVD_TPU_FLASH_RESIDENT_SEQ", "0")
    q, k, v = _qkv(s=96, d=16)
    o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(o - ref)) < TOL

    w = jnp.cos(jnp.arange(16))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) * w)

    def ref_f(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * w)

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_f, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert jnp.max(jnp.abs(a - b)) < 5e-4


def test_bfloat16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    o = flash_attention(q, k, v, causal=True)
    assert o.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, causal=True)
    diff = jnp.max(jnp.abs(o.astype(jnp.float32)
                           - ref.astype(jnp.float32)))
    assert diff < 0.05  # bf16 mantissa tolerance
