"""hvd-spec: speculative decoding with the bitwise-greedy acceptance
kernel, and its composition with the shared-prefix page cache.

The load-bearing assertion (ISSUE 15 acceptance): speculative greedy
completions are BITWISE-equal to non-speculative greedy completions —
for ANY draft model (the acceptance rule gates every token through the
target's verify logits, which are bitwise-equal to the decode
executable's at every position), any acceptance pattern, any batch
mix, and across an elastic drain/resume.  The draft only ever moves
wall-clock, never tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (TransformerConfig,
                                            init_transformer,
                                            serving_forward)
from horovod_tpu.serving import InferenceEngine, Request
from horovod_tpu.serving import harness as _harness

CFG = TransformerConfig(vocab_size=97, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq_len=64)
PARAMS = init_transformer(jax.random.PRNGKey(0), CFG)
# A RANDOM draft: its proposals are essentially uncorrelated with the
# target's greedy tokens (acceptance ~0) — the adversarial case for
# the bitwise contract.
DRAFT_CFG = TransformerConfig(vocab_size=97, d_model=32, n_heads=2,
                              n_layers=1, d_ff=64, max_seq_len=64)
DRAFT = init_transformer(jax.random.PRNGKey(9), DRAFT_CFG)


def agreement_pair():
    """(target, draft) with deterministic acceptance 1.0 — the shared
    serving.harness construction (ONE implementation with the bench's
    CI gate)."""
    tcfg = CFG
    dcfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=4,
                             n_layers=1, d_ff=32, max_seq_len=64)
    tparams, dparams = _harness.agreement_pair(tcfg, dcfg)
    return (tparams, tcfg), (dparams, dcfg)


def make_engine(params=PARAMS, cfg=CFG, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("page_size", 8)
    kw.setdefault("capacity", 32)
    return InferenceEngine(params, cfg, **kw)


def make_spec_engine(**kw):
    kw.setdefault("draft", (DRAFT, DRAFT_CFG))
    kw.setdefault("spec_tokens", 3)
    return make_engine(**kw)


# Warm engines are the dominant test cost (each warm_start AOT-compiles
# decode + propose + verify); tests that leave the engine idle share
# these module-scoped ones.  Tests that drain, relaunch, or need
# bespoke shapes still build their own.
_CACHED = {}


def spec_eng():
    if "spec" not in _CACHED:
        e = make_spec_engine()
        e.warm_start()
        _CACHED["spec"] = e
    return _CACHED["spec"]


def base_eng():
    if "base" not in _CACHED:
        e = make_engine()
        e.warm_start()
        _CACHED["base"] = e
    return _CACHED["base"]


def agree_eng():
    if "agree" not in _CACHED:
        (tp, tc), (dp, dc) = agreement_pair()
        e = make_engine(tp, tc, draft=(dp, dc), spec_tokens=3)
        e.warm_start()
        _CACHED["agree"] = (e, tp, tc)
    return _CACHED["agree"]


def reference_rollout(prompt, n, capacity, params=PARAMS, cfg=CFG):
    sf = jax.jit(serving_forward, static_argnums=(2, 3))
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = np.asarray(sf(params, jnp.asarray([seq], jnp.int32),
                               cfg, capacity))
        tok = int(np.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


# ---------------------------------------------------------------------------
# The bitwise-greedy acceptance contract
# ---------------------------------------------------------------------------

def test_spec_bitwise_with_uncorrelated_draft():
    """ANY draft yields bitwise non-speculative completions — here an
    uncorrelated one whose proposals are almost always rejected, so
    every iteration exercises the rejection/rollback path."""
    eng = spec_eng()
    prompts = [[5, 3, 8], [1, 2, 3, 4, 5, 6], [9, 9, 2, 6]]
    ref = [reference_rollout(p, 7, eng.capacity) for p in prompts]
    assert [eng.generate(list(p), max_new_tokens=7)
            for p in prompts] == ref
    # Concurrent: the three share the decode batch; completions are
    # invariant to batch composition under speculation too.
    reqs = [eng.submit(list(p), max_new_tokens=7) for p in prompts]
    eng.run_until_idle()
    assert [r.result(0) for r in reqs] == ref
    # The uncorrelated draft's acceptance really is low — the test
    # above exercised rejection, not a lucky always-accept draft.
    assert eng.spec_acceptance_rate is not None
    assert eng.spec_acceptance_rate < 0.5


@pytest.mark.slow
@pytest.mark.parametrize("spec_tokens", [1, 3, 5])
def test_spec_depth_never_changes_tokens(spec_tokens):
    eng = make_spec_engine(spec_tokens=spec_tokens)
    eng.warm_start()
    ref = reference_rollout([7, 1, 4], 9, eng.capacity)
    assert eng.generate([7, 1, 4], max_new_tokens=9) == ref


def test_spec_full_acceptance_emits_blocks():
    """The agreement pair accepts every proposal: each iteration emits
    spec_tokens + 1 tokens, and the completions still match the
    target's own reference rollout bitwise."""
    eng, tp, tc = agree_eng()
    ref = reference_rollout([5, 3, 8], 12, eng.capacity, tp, tc)
    req = eng.submit([5, 3, 8], max_new_tokens=12)
    iters = 0
    while not eng.scheduler.idle():
        eng.step()
        iters += 1
    assert req.result(0) == ref
    assert eng.spec_acceptance_rate == 1.0
    # 12 tokens: 1 at prefill + 11 through blocks of <= 4 -> the first
    # step (admission+block) plus at most 2 more iterations.
    assert iters <= 4


def test_spec_steady_state_is_one_propose_one_verify_dispatch():
    """Dispatch contract under speculation: a steady-state iteration is
    exactly ONE draft propose + ONE target verify executable call,
    with zero eager launches — the decode path's megakernel discipline
    carried over (verify included in the one target dispatch)."""
    eng = spec_eng()
    for p in ([1, 2, 3], [4, 5, 6, 7]):
        eng.submit(list(p), max_new_tokens=8)
    eng.step()  # admissions + prefills + first block
    proposes, verifies, eager = _harness.count_spec_dispatches(eng)
    assert (proposes, verifies) == (1, 1), (proposes, verifies)
    assert eager == 0, (
        f"{eager} eager dispatches leaked out of the speculative "
        f"iteration")
    eng.run_until_idle()


def test_spec_eos_mid_block_stops_exactly_at_eos():
    """EOS landing inside an accepted block: the tokens after it are
    discarded exactly as non-speculative decode would never have
    produced them."""
    eng, tp, tc = agree_eng()
    ref = reference_rollout([5, 3, 8], 12, 32, tp, tc)
    # Stop on the 4th reference token — mid-block at full acceptance
    # (the first block after prefill emits ref[1..4]).
    out = eng.generate([5, 3, 8], max_new_tokens=12, eos_id=ref[3])
    assert out == ref[:4]


@pytest.mark.slow
@pytest.mark.parametrize("use_agreement", [False, True])
def test_spec_capacity_finish_is_bitwise(use_agreement):
    """A CAPACITY-finished speculative rollout (blocks written at the
    view's edge, trash-dropped past it) matches the non-incremental
    reference bitwise."""
    if use_agreement:
        (tp, tc), (dp, dc) = agreement_pair()
    else:
        (tp, tc), (dp, dc) = (PARAMS, CFG), (DRAFT, DRAFT_CFG)
    eng = make_engine(tp, tc, draft=(dp, dc), spec_tokens=3)
    eng.warm_start()
    prompt = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(7), (eng.capacity - 5,), 0, tc.vocab_size)]
    req = eng.submit(list(prompt), max_new_tokens=99)
    eng.run_until_idle()
    out = req.result(0)
    assert req.finish_reason == "capacity"
    assert len(prompt) + len(out) == eng.capacity
    assert out == reference_rollout(prompt, len(out), eng.capacity,
                                    tp, tc)


def test_spec_mixed_batch_with_temperature_slot():
    """Mixed speculative/non-speculative batch: greedy slots ride the
    acceptance rule, a temperature slot samples from the block's first
    position — bitwise what the non-speculative engine samples."""
    eng = spec_eng()
    base = base_eng()
    greedy_ref = reference_rollout([5, 3, 8], 6, eng.capacity)
    temp_base = base.generate([2, 4, 6], max_new_tokens=6,
                              temperature=0.8, seed=17)
    r_greedy = eng.submit([5, 3, 8], max_new_tokens=6)
    r_temp = eng.submit([2, 4, 6], max_new_tokens=6, temperature=0.8,
                        seed=17)
    eng.run_until_idle()
    assert r_greedy.result(0) == greedy_ref
    assert r_temp.result(0) == temp_base


def test_spec_drain_resume_reproduces_uninterrupted_rollout():
    """Elastic drain mid-speculation → export → fresh spec engine →
    import: the stitched completion equals the uninterrupted one (and
    the non-speculative reference)."""
    ref = reference_rollout([3, 1, 4, 1, 5], 10, 32)
    eng = make_spec_engine()
    eng.warm_start()
    req = eng.submit([3, 1, 4, 1, 5], max_new_tokens=10)
    eng.step()
    eng.step()  # a couple of speculative iterations in
    exported = eng.drain()
    assert exported and req.finish_reason == "drained"
    eng2 = make_spec_engine()
    eng2.warm_start()
    [req2] = eng2.import_requests(exported)
    eng2.run_until_idle()
    assert req2.result(0) == ref


def test_spec_client_disconnect_releases_draft_and_target_slots():
    """abort_request mid-speculation: the iteration-boundary eviction
    frees the slot's pages on BOTH stores and decrements the prefix
    refcounts — nothing leaks."""
    eng = spec_eng()
    req = eng.submit(list(range(1, 18)), max_new_tokens=50)
    eng.step()
    assert eng.scheduler.occupancy() == 1
    assert eng.abort_request(req) == "active"
    eng.step()  # the boundary eviction
    assert req.finish_reason == "client_disconnect"
    assert eng.cache.free_pages() == eng.cache.total_pages
    assert eng.draft_cache.free_pages() == eng.draft_cache.total_pages
    assert eng.cache.prefix_stats()["referenced_pages"] == 0


def test_spec_composes_with_prefix_cache():
    """Prefix hit + speculation together: the second request maps the
    first's header pages copy-free AND speculates — completions stay
    bitwise-equal to the plain engine with both features off."""
    header = list(range(1, 17))  # two full pages at page_size=8
    # Ground truth: the non-incremental reference (≡ a cache-off
    # engine, per the standing contract).
    a_ref = reference_rollout(header + [20, 21], 6, 32)
    b_ref = reference_rollout(header + [30, 31, 32], 6, 32)
    eng = spec_eng()
    assert eng.generate(header + [20, 21], max_new_tokens=6) == a_ref
    before = eng.cache.prefix_stats()["cached_pages"]
    assert before >= 2
    assert eng.generate(header + [30, 31, 32],
                        max_new_tokens=6) == b_ref


def test_spec_draft_store_takes_prefix_hits_of_its_own():
    """hvd-route satellite: the DRAFT KV store rides the shared-prefix
    index too — a repeated header maps copy-free on BOTH stores, and
    the draft's hits count on the split ``serving.prefix_hits_draft``
    counter (hvd-tune's hit-rate sensor sums the two)."""
    from horovod_tpu import telemetry as _telemetry

    def draft_hits():
        return _telemetry.metrics().get(
            "serving.prefix_hits_draft", {}).get("value", 0)

    header = list(range(40, 56))  # two full pages at page_size=8
    eng = spec_eng()
    eng.generate(header + [60, 61], max_new_tokens=4)
    # The first request published the header pages on both stores.
    assert len(eng.draft_cache.lookup_prefix(header + [70])) == 2
    assert eng.draft_cache.prefix_stats()["cached_pages"] >= 2
    h0 = draft_hits()
    ref = reference_rollout(header + [70, 71], 4, 32)
    assert eng.generate(header + [70, 71], max_new_tokens=4) == ref
    assert draft_hits() - h0 == 1


@pytest.mark.slow
def test_spec_warm_start_records_and_rebuilds_executables(tmp_path,
                                                          monkeypatch):
    """The manifest records verify/draft_propose/draft_prefill entries
    keyed to the draft model + speculation depth; a fresh engine's
    warm_start rebuilds them BEFORE any request, and an engine with a
    different depth skips the foreign entries."""
    import json as _json

    monkeypatch.setenv("HVD_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    e1 = make_spec_engine()
    e1.warm_start()
    out1 = e1.generate([1, 2, 3, 4, 5], max_new_tokens=6)
    man = _json.loads(
        (tmp_path / "megakernel_manifest.json").read_text())
    kinds = {(e["kind"], e.get("bucket")) for e in man["entries"]
             if e["variant"] == "serving"}
    assert ("verify", 4) in kinds and ("draft_propose", 3) in kinds
    assert any(k == "draft_prefill" for k, _ in kinds)

    e2 = make_spec_engine()
    warmed = e2.warm_start(str(tmp_path))
    assert warmed >= 3
    assert ("verify", 4) in e2._exec
    assert ("draft_propose", 3) in e2._exec
    assert e2.generate([1, 2, 3, 4, 5], max_new_tokens=6) == out1

    # Different speculation depth: the spec executables are foreign
    # (not rebuilt from the manifest), but warm_start still builds its
    # own fresh pair.
    e3 = make_spec_engine(spec_tokens=2)
    e3.warm_start(str(tmp_path))
    assert ("verify", 3) in e3._exec
    assert ("verify", 4) not in e3._exec


def test_spec_health_reports_speculation():
    eng = spec_eng()
    ready, payload = eng.health()
    assert ready and payload["speculative"] is True
    assert payload["spec_tokens"] == 3
    _, payload2 = base_eng().health()
    assert payload2["speculative"] is False


def test_spec_telemetry_counters_flow():
    from horovod_tpu import telemetry as _telemetry

    def counter(name):
        return _telemetry.metrics().get(name, {}).get("value", 0)

    before_p = counter("serving.spec_proposed")
    before_a = counter("serving.spec_accepted")
    eng, _tp, _tc = agree_eng()
    eng.generate([5, 3, 8], max_new_tokens=8)
    proposed = counter("serving.spec_proposed") - before_p
    accepted = counter("serving.spec_accepted") - before_a
    assert proposed > 0 and accepted > 0
    assert counter("serving.spec_acceptance_rate") > 0.0


def test_spec_tokens_env_zero_is_fine_without_a_draft(monkeypatch):
    """HVD_TPU_SPEC_TOKENS=0 (the natural 'speculation off' setting)
    must not break draft-less engines — the depth is unused there."""
    monkeypatch.setenv("HVD_TPU_SPEC_TOKENS", "0")
    eng = make_engine()
    assert eng.spec_tokens == 0
    with pytest.raises(ValueError, match="spec_tokens"):
        make_engine(draft=(DRAFT, DRAFT_CFG))  # armed -> validated


def test_spec_all_temperature_batch_falls_back_to_decode():
    """An iteration with no greedy slot runs plain decode: sampled
    slots never consult proposals, so propose + wide verify would be
    pure overhead."""
    eng = spec_eng()
    req = eng.submit([4, 4, 4], max_new_tokens=4, temperature=0.7,
                     seed=5)
    eng.step()
    proposes = {"n": 0}
    pkey = ("draft_propose", 3)
    p_exec = eng._exec[pkey]
    eng._exec[pkey] = lambda *a: (
        proposes.__setitem__("n", proposes["n"] + 1) or p_exec(*a))
    eng.run_until_idle()
    eng._exec[pkey] = p_exec
    assert proposes["n"] == 0
    base = base_eng()
    assert req.result(0) == base.generate([4, 4, 4], max_new_tokens=4,
                                          temperature=0.7, seed=5)


def test_seed_prefixes_failure_frees_ghost_pages():
    """A prefill that raises mid-seed must return the ghost pages to
    the free list and let the restore continue with the next chain."""
    eng = make_engine(prefix_cache=True)
    eng.warm_start()
    free_before = eng.cache.free_pages()

    orig = eng._prefill_exec
    calls = {"n": 0}

    def failing(bucket, draft=False):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("seeded prefill failure")
        return orig(bucket, draft)

    eng._prefill_exec = failing
    seeded = eng.seed_prefixes([list(range(16)),
                                list(range(50, 66))])
    eng._prefill_exec = orig
    # First chain failed and freed its pages; second seeded.
    assert seeded == 2
    assert eng.cache.free_pages() == free_before
    assert eng.cache.prefix_stats()["cached_pages"] == 2


def test_spec_rejects_bad_draft_configs():
    bad_vocab = TransformerConfig(vocab_size=50, d_model=32, n_heads=2,
                                  n_layers=1, d_ff=64, max_seq_len=64)
    with pytest.raises(ValueError, match="vocab_size"):
        make_engine(draft=(DRAFT, bad_vocab))
    short = TransformerConfig(vocab_size=97, d_model=32, n_heads=2,
                              n_layers=1, d_ff=64, max_seq_len=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        make_engine(draft=(DRAFT, short))
    with pytest.raises(ValueError, match="spec_tokens"):
        make_spec_engine(spec_tokens=0)


# ---------------------------------------------------------------------------
# Planner what-ifs (hvd-mem satellite)
# ---------------------------------------------------------------------------

def test_planner_draft_and_prefix_whatifs_match_runtime():
    """--draft-layers / --prefix-pages share the runtime byte
    formulas: the plan's serving.prefix_pages equals the cache's
    construction-time ledger partition exactly, serving.draft_kv the
    draft cache's charge, and serving.draft_params the actual
    init_transformer tree bytes."""
    from horovod_tpu.memory import ledger as led
    from horovod_tpu.memory import planner
    from horovod_tpu.serving.kv_cache import PagedKVCache

    led.ledger.reset()
    cache = PagedKVCache(2, 4, 16, max_slots=4, pages_per_slot=4,
                         page_size=8, prefix_cache=True, prefix_pages=6)
    got = led.ledger.bytes_by_category()
    assert cache.n_pages == 1 + 16 + 6  # trash + slots + prefix reserve
    plan = planner.plan_serving(
        n_layers=2, n_heads=4, head_dim=16, max_slots=4,
        pages_per_slot=4, page_size=8, prefix_pages=6, draft_layers=1,
        vocab_size=97)
    fw = plan.framework
    assert got["serving.kv_pages"] == fw["serving.kv_pages"]
    assert got["serving.prefix_pages"] == fw["serving.prefix_pages"]
    dcfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=4,
                             n_layers=1, d_ff=256, max_seq_len=32)
    dp = init_transformer(jax.random.PRNGKey(0), dcfg)
    actual = sum(x.nbytes for x in jax.tree_util.tree_leaves(dp))
    assert fw["serving.draft_params"] == actual
    led.ledger.reset()


def test_planner_cli_accepts_spec_knobs():
    from horovod_tpu.memory.__main__ import main as mem_main

    rc = mem_main(["--plan", "--model", "serving", "--draft-layers",
                   "1", "--prefix-pages", "8"])
    assert rc == 0


def test_draft_ledger_categories_live_and_release():
    from horovod_tpu.memory import ledger as led

    led.ledger.reset()
    eng = make_spec_engine()
    got = led.ledger.bytes_by_category()
    assert got.get("serving.draft_kv", 0) > 0
    assert got.get("serving.draft_params", 0) > 0
    expected = sum(x.nbytes for x in
                   jax.tree_util.tree_leaves(eng._draft_params))
    assert got["serving.draft_params"] == expected
