"""Backward/communication overlap (parallel/overlap.py, ISSUE 8).

The bucketed-backward train step streams each gradient bucket's
pack→reduce→unpack megakernel out of the backward pass instead of
waiting for the full gradient pytree.  Its load-bearing contracts:

* bitwise identity: the overlapped step's parameters equal the
  monolithic ``HVD_TPU_OVERLAP=off`` step's, bitwise, for the
  single-backward streaming schedule, across leaf dtypes; the
  segmented schedule equals the serialized dispatch of the same
  sub-programs bitwise (same programs, different interleaving);
* steady state: exactly one megakernel launch per bucket per cycle,
  with the response cache replaying every bucket's sub-program (no
  renegotiation after warmup) — counted at jax's real dispatch choke
  point (utils/xla_dispatch, same policy as tests/test_megakernel.py);
* per-bucket error-feedback residuals survive the partial-cycle
  refactor (int8 wire: overlapped ≡ serialized bitwise across steps);
* a fusion-threshold change re-partitions the dispatch boundaries
  (the same event that flushes the coordinator plan memo);
* unbucketable trees (sparse IndexedSlices leaves, Adasum, subset
  meshes) fall back to the monolithic step.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu.core.state as state_mod
import horovod_tpu.ops.megakernel as mk
from horovod_tpu.core.state import REPLICA_AXIS
from horovod_tpu.ops import compression as compression_mod
from horovod_tpu.ops.sparse import IndexedSlices
from horovod_tpu.parallel import overlap as OV
from horovod_tpu.parallel.training import make_train_step, shard_batch

# ---------------------------------------------------------------------------
# Fixtures: a plain loss (unsegmented schedule) and a 3-stage chain
# (segmented schedule), sized so each segment splits into two buckets
# at _THRESHOLD (b-leaves bucket apart from the w-leaves).
# ---------------------------------------------------------------------------

_DIM = 64
_THRESHOLD = _DIM * _DIM * 4  # one f32 [64, 64] weight fills a bucket


def _plain_loss(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = h @ params["w2"] + params["b2"]
    return jnp.mean((pred - y) ** 2)


def _plain_params(key, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    s = 1.0 / np.sqrt(_DIM)
    return {
        "w1": (jax.random.normal(k1, (_DIM, _DIM)) * s).astype(dtype),
        "b1": jnp.zeros((_DIM,), dtype),
        "w2": (jax.random.normal(k2, (_DIM, _DIM)) * s).astype(dtype),
        "b2": jnp.zeros((_DIM,), dtype),
    }


def _chain():
    def stage0(p, carry, batch):
        x, _y = batch
        return jnp.tanh(x @ p["w"] + p["b"])

    def stage1(p, carry, batch):
        return jnp.tanh(carry @ p["w"] + p["b"])

    def stage2(p, carry, batch):
        _x, y = batch
        pred = carry @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2)

    return OV.ChainedLoss([stage0, stage1, stage2])


def _chain_params(key):
    ks = jax.random.split(key, 3)
    s = 1.0 / np.sqrt(_DIM)
    return [{"w": jax.random.normal(k, (_DIM, _DIM)) * s,
             "b": jnp.zeros((_DIM,))} for k in ks]


def _batch(hvd, key, per=4):
    n = hvd.size()
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (per * n, _DIM))
    y = jax.random.normal(ky, (per * n, _DIM))
    return shard_batch((x, y))


def _leaves_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
               for x, y in zip(fa, fb))


def _run(step, params, opt, batch, steps):
    p, s = params, opt.init(params)
    loss = None
    for _ in range(steps):
        out = step(p, s, batch)
        p, s, loss = out[0], out[1], out[2]
    jax.block_until_ready(jax.tree_util.tree_leaves(p))
    return p, float(loss)


# ---------------------------------------------------------------------------
# Bitwise identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_stream_bitwise_identical_to_monolithic(hvd, dtype):
    """The streaming schedule's params ≡ the monolithic step's, bitwise,
    after several steps — per leaf dtype (buckets partition by wire
    dtype, so each dtype rides its own megakernels)."""
    params = _plain_params(jax.random.PRNGKey(0), dtype)
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.adam(1e-3)
    p_on, l_on = _run(make_train_step(
        _plain_loss, opt, donate=False, fusion_threshold=_THRESHOLD,
        overlap="on"), params, opt, batch, 3)
    p_off, l_off = _run(make_train_step(
        _plain_loss, opt, donate=False, fusion_threshold=_THRESHOLD,
        overlap="off"), params, opt, batch, 3)
    assert l_on == l_off
    assert _leaves_equal(p_on, p_off)


def test_stream_bitwise_identical_mixed_dtypes(hvd):
    """One tree mixing f32 and bf16 leaves: the bucket plan groups by
    dtype and the result stays bitwise vs the monolithic step."""
    params = _plain_params(jax.random.PRNGKey(0))
    params["b1"] = params["b1"].astype(jnp.bfloat16)
    params["b2"] = params["b2"].astype(jnp.bfloat16)
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    p_on, _ = _run(make_train_step(
        _plain_loss, opt, donate=False, fusion_threshold=_THRESHOLD,
        overlap="on"), params, opt, batch, 2)
    p_off, _ = _run(make_train_step(
        _plain_loss, opt, donate=False, fusion_threshold=_THRESHOLD,
        overlap="off"), params, opt, batch, 2)
    assert _leaves_equal(p_on, p_off)


def test_segmented_stream_equals_serialized_bitwise(hvd):
    """ChainedLoss: the streamed dispatch ≡ the serialized dispatch of
    the SAME per-bucket sub-programs, bitwise (structural — identical
    programs, different interleaving), and ≈ the monolithic step
    (XLA:CPU compiles per-stage backward programs a ULP apart from the
    fused whole-program backward; see parallel/overlap.py)."""
    chain = _chain()
    params = _chain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.adam(1e-3)

    def build(mode):
        return make_train_step(chain, opt, donate=False,
                               fusion_threshold=_THRESHOLD, overlap=mode)

    step_on = build("on")
    p_on, _ = _run(step_on, params, opt, batch, 3)
    p_ser, _ = _run(build("serial"), params, opt, batch, 3)
    p_off, _ = _run(build("off"), params, opt, batch, 3)
    assert step_on.overlap_active
    assert step_on.segment_count == 3
    assert step_on.bucket_count == 6  # (w, b) buckets per stage
    assert _leaves_equal(p_on, p_ser)
    for a, b in zip(jax.tree_util.tree_leaves(p_on),
                    jax.tree_util.tree_leaves(p_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_overlap_off_restores_static_step(hvd, monkeypatch):
    """HVD_TPU_OVERLAP=off (and the pre-PR default on CPU meshes via
    auto) builds the plain jitted program — no overlap machinery at
    all."""
    opt = optax.sgd(0.1)
    step_off = make_train_step(_plain_loss, opt, donate=False,
                               overlap="off")
    assert not hasattr(step_off, "overlap_active")
    monkeypatch.delenv(OV.OVERLAP_ENV, raising=False)
    step_auto = make_train_step(_plain_loss, opt, donate=False)
    assert not hasattr(step_auto, "overlap_active")  # auto→off on CPU


# ---------------------------------------------------------------------------
# Steady state: one launch per bucket, response-cache replay
# ---------------------------------------------------------------------------

def test_exactly_one_launch_per_bucket_and_cache_replay(hvd):
    """After warmup, one training cycle issues exactly one megakernel
    launch per bucket — counted at jax's dispatch choke point — and
    every bucket's sub-program replays from the response cache (zero
    new negotiations)."""
    from horovod_tpu.utils import xla_dispatch

    chain = _chain()
    params = _chain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    step = make_train_step(chain, opt, donate=False,
                           fusion_threshold=_THRESHOLD, overlap="on")
    mk.set_enabled(True)
    p, s = params, opt.init(params)
    for _ in range(2):  # cold + first warm cycle
        p, s, _ = step(p, s, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(p))

    st = state_mod.global_state()
    n_buckets = step.bucket_count
    n_leaves = len(jax.tree_util.tree_leaves(params))
    launches0 = mk.stats.launches
    cache0 = st.response_cache.stats.replayed_tensors
    misses0 = st.response_cache.stats.misses
    with xla_dispatch.exact_scope():
        with xla_dispatch.record(all_threads=True) as scope:
            p, s, _ = step(p, s, batch)
            jax.block_until_ready(jax.tree_util.tree_leaves(p))

    assert mk.stats.launches - launches0 == n_buckets, (
        f"steady-state cycle ran {mk.stats.launches - launches0} "
        f"megakernel launches for {n_buckets} buckets")
    # Choke-point accounting: 1 forward + one backward program per
    # segment + one megakernel per bucket + 1 optimizer apply.  Any
    # eager-op creep on the dispatch path breaks this equality.
    expected = 1 + step.segment_count + n_buckets + 1
    assert scope.count == expected, (
        f"steady-state cycle issued {scope.count} XLA dispatches; "
        f"expected {expected} (fwd + {step.segment_count} bwd + "
        f"{n_buckets} megakernels + apply)")
    # Replay bypassed negotiation for every bucket (per-bucket
    # sub-programs are fully cache-hit: no new misses).
    assert st.response_cache.stats.replayed_tensors - cache0 == n_leaves
    assert st.response_cache.stats.misses == misses0


def test_telemetry_counters_and_timeline_instants(hvd, tmp_path):
    """overlap.buckets_dispatched counts every bucket handed to the
    dynamic path; overlap.exposed_comm_seconds records the post-backward
    completion wait; each dispatch writes a BUCKET_DISPATCH timeline
    instant."""
    import horovod_tpu as H

    chain = _chain()
    params = _chain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    step = make_train_step(chain, opt, donate=False,
                           fusion_threshold=_THRESHOLD, overlap="on")
    base = H.metrics().get("overlap.buckets_dispatched", {}).get("value", 0)
    tl_path = tmp_path / "overlap_timeline.json"
    H.start_timeline(str(tl_path))
    try:
        _run(step, params, opt, batch, 2)
    finally:
        H.stop_timeline()
    snap = H.metrics()
    dispatched = snap["overlap.buckets_dispatched"]["value"] - base
    assert dispatched == 2 * step.bucket_count
    assert snap["overlap.exposed_comm_seconds"]["count"] >= 2
    events = json.loads(tl_path.read_text())
    if isinstance(events, dict):
        events = events["traceEvents"]
    instants = [e for e in events if e.get("name") == "BUCKET_DISPATCH"]
    assert len(instants) == dispatched
    assert {e["args"]["bucket"] for e in instants} \
        == set(range(step.bucket_count))


# ---------------------------------------------------------------------------
# Quantized wire: per-bucket error-feedback residuals
# ---------------------------------------------------------------------------

def test_int8_ef_residuals_carry_over_per_bucket(hvd):
    """Under int8 wire compression the streamed schedule stays bitwise
    equal to the serialized schedule across steps — only true when each
    bucket's error-feedback residual is stored and re-consumed under
    its own (per-bucket sub-program) key, and the residual actually
    carries: the quantized trajectory must diverge from full precision."""
    import horovod_tpu as H

    chain = _chain()
    params = _chain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.adam(1e-3)

    def build(mode):
        return make_train_step(chain, opt, donate=False,
                               fusion_threshold=_THRESHOLD, overlap=mode)

    p_fp, _ = _run(build("on"), params, opt, batch, 3)
    H.set_compression(default="int8")
    try:
        step_on = build("on")
        p_on, _ = _run(step_on, params, opt, batch, 3)
        p_ser, _ = _run(build("serial"), params, opt, batch, 3)
        # One EF residual entry per bucket survives for the next step.
        assert mk.residual_count() >= step_on.bucket_count
    finally:
        H.set_compression(default="none")
    assert _leaves_equal(p_on, p_ser)
    assert not _leaves_equal(p_on, p_fp)  # the wire really quantized


# ---------------------------------------------------------------------------
# Fusion-threshold flush
# ---------------------------------------------------------------------------

def test_fusion_threshold_change_replans_buckets(hvd):
    """set_fusion_threshold mid-training (the autotune event that
    flushes the coordinator plan memo and the megakernel cache) makes
    the overlapped step re-partition its dispatch boundaries on the
    next call — and the result stays bitwise vs the monolithic step."""
    params = _plain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    opt = optax.sgd(0.1)
    st = state_mod.global_state()
    st.coordinator.set_fusion_threshold(_THRESHOLD)
    try:
        step = make_train_step(_plain_loss, opt, donate=False,
                               overlap="on")
        p, s = params, opt.init(params)
        p, s, _ = step(p, s, batch)
        coarse = step.bucket_count
        # Below one bias leaf (256 B): every leaf becomes its own bucket.
        st.coordinator.set_fusion_threshold(128)
        p, s, _ = step(p, s, batch)
        fine = step.bucket_count
        assert fine > coarse, (coarse, fine)

        # Same two-threshold trajectory on the monolithic step: the
        # re-planned buckets still reduce to identical parameters.
        st.coordinator.set_fusion_threshold(_THRESHOLD)
        step_off = make_train_step(_plain_loss, opt, donate=False,
                                   overlap="off")
        q, t = params, opt.init(params)
        q, t, _ = step_off(q, t, batch)
        st.coordinator.set_fusion_threshold(128)
        q, t, _ = step_off(q, t, batch)
        assert _leaves_equal(p, q)
    finally:
        st.coordinator.set_fusion_threshold(64 * 1024 * 1024)


# ---------------------------------------------------------------------------
# Fallbacks: unbucketable trees keep the monolithic program, and every
# fallback leaves the triple-entry record — ONE overlap.fallbacks
# counter tick and ONE overlap_fallback flight event, carrying the
# NAMED reason (the warn line rides stderr).
# ---------------------------------------------------------------------------

def _fallback_events():
    from horovod_tpu.telemetry import flight

    return [e for e in flight.snapshot() if e[1] == "overlap_fallback"]


def _fallbacks_counter():
    import horovod_tpu as H

    return H.metrics().get("overlap.fallbacks", {}).get("value", 0)


def _assert_fell_back_once(step, reason, counter0, events0):
    assert step.overlap_active is False
    assert step._fallback_reason == reason
    assert _fallbacks_counter() - counter0 == 1
    new = _fallback_events()[events0:]
    assert len(new) == 1, new
    assert new[0][2][0] == reason, new


def test_sparse_gradient_leaves_fall_back(hvd):
    """IndexedSlices gradient leaves ship a negotiated-size payload the
    bucket planner cannot size: the trace-time probe refuses them."""
    opt = optax.sgd(0.1)
    step = make_train_step(_plain_loss, opt, donate=False, overlap="on")

    def sparse_grad_fn(params, batch):
        grads = dict(params)
        grads["w1"] = IndexedSlices(jnp.zeros((2, _DIM)),
                                    jnp.zeros((2,), jnp.int32),
                                    (_DIM, _DIM))
        return jnp.zeros(()), grads

    with pytest.raises(OV._Unbucketable, match="sparse") as ei:
        step._detect_sparse(sparse_grad_fn,
                            _plain_params(jax.random.PRNGKey(0)), None,
                            _batch(hvd, jax.random.PRNGKey(1)))
    assert ei.value.reason == "sparse"


def test_sparse_fallback_counts_and_flight_records_once(hvd, monkeypatch):
    """The sparse refusal surfaces through the step as the named
    ``sparse`` fallback: counter and flight event exactly once."""
    opt = optax.sgd(0.1)
    step = make_train_step(_plain_loss, opt, donate=False, overlap="on")
    monkeypatch.setattr(
        OV._OverlapStep, "_detect_sparse",
        lambda self, *a: (_ for _ in ()).throw(OV._Unbucketable(
            "sparse", "seeded sparse leaf")))
    c0, e0 = _fallbacks_counter(), len(_fallback_events())
    params = _plain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    p, s, _loss = step(params, opt.init(params), batch)
    step(p, s, batch)  # second step: no new record
    _assert_fell_back_once(step, "sparse", c0, e0)


def test_adasum_fallback_counts_and_flight_records_once(hvd):
    """op=Adasum combines the WHOLE gradient vector — no per-bucket
    decomposition exists, so the first call falls back to the static
    step under the named ``adasum`` reason (counted + flight-recorded
    exactly once, further steps free)."""
    import horovod_tpu as H

    opt = optax.sgd(0.1)
    step = make_train_step(_plain_loss, opt, donate=False, op=H.Adasum,
                           overlap="on")
    c0, e0 = _fallbacks_counter(), len(_fallback_events())
    params = _plain_params(jax.random.PRNGKey(0))
    batch = _batch(hvd, jax.random.PRNGKey(1))
    p, s, loss = step(params, opt.init(params), batch)
    p, s, loss = step(p, s, batch)  # second step: no new record
    assert np.isfinite(float(loss))
    _assert_fell_back_once(step, "adasum", c0, e0)


def test_subset_mesh_falls_back(hvd):
    """A step built over a sub-mesh of the global replica set keeps its
    in-program reduction (the dynamic path negotiates over ALL
    replicas); results match the monolithic sub-mesh step bitwise, and
    the fallback records once under the named ``sub-mesh`` reason."""
    devices = jax.devices()[:4]
    mesh = jax.sharding.Mesh(np.asarray(devices), (REPLICA_AXIS,))
    params = _plain_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4 * len(devices), _DIM))
    y = jax.random.normal(jax.random.PRNGKey(2), (4 * len(devices), _DIM))
    opt = optax.sgd(0.1)
    step = make_train_step(_plain_loss, opt, mesh=mesh, donate=False,
                           overlap="on")
    c0, e0 = _fallbacks_counter(), len(_fallback_events())
    p_on, _ = _run(step, params, opt, (x, y), 2)
    _assert_fell_back_once(step, "sub-mesh", c0, e0)
    step_off = make_train_step(_plain_loss, opt, mesh=mesh, donate=False,
                               overlap="off")
    p_off, _ = _run(step_off, params, opt, (x, y), 2)
    assert _leaves_equal(p_on, p_off)


def test_mp_is_not_a_fallback_anymore(hvd, monkeypatch):
    """After this PR a plain multi-process build (one replica per
    process, aligned meshes) passes the build gates and proceeds to
    the bucketed path — asserted by faking the mp state flags and
    watching the build reach plan construction instead of falling
    back with an ``mp`` reason.  (The real np=2 bitwise leg rides
    tests/mp_worker.py scenario_overlap under CI's jax.)"""
    import horovod_tpu.ops.collective as C
    import horovod_tpu.core.state as state_mod

    st = state_mod.global_state()
    monkeypatch.setattr(st, "multiprocess", True)
    monkeypatch.setattr(st, "process_count", st.size)
    monkeypatch.setattr(C, "_mp_kernels",
                        lambda: (st.mesh, None))
    opt = optax.sgd(0.1)
    step = make_train_step(_plain_loss, opt, donate=False, overlap="on")
    reached = {}

    def probe(self, *a):
        reached["build"] = True
        raise OV._Unbucketable("grad-tree", "stop before any transport")

    monkeypatch.setattr(OV._OverlapStep, "_build_unsegmented", probe)
    c0, e0 = _fallbacks_counter(), len(_fallback_events())
    params = _plain_params(jax.random.PRNGKey(0))
    step(params, opt.init(params), _batch(hvd, jax.random.PRNGKey(1)))
    assert reached.get("build"), "mp build gate still falls back"
    _assert_fell_back_once(step, "grad-tree", c0, e0)


# ---------------------------------------------------------------------------
# Env knob: validation, resolution, HELLO fingerprint
# ---------------------------------------------------------------------------

def test_env_knob_validation(monkeypatch):
    monkeypatch.setenv(OV.OVERLAP_ENV, "bogus")
    with pytest.raises(ValueError, match="HVD_TPU_OVERLAP"):
        OV.validate_env()
    for ok in ("auto", "on", "off", "serial", "1", "0", "ON", " off "):
        monkeypatch.setenv(OV.OVERLAP_ENV, ok)
        OV.validate_env()
    monkeypatch.setenv(OV.OVERLAP_ENV, "1")
    assert OV.overlap_mode() == "on"
    monkeypatch.setenv(OV.OVERLAP_ENV, "0")
    assert OV.overlap_mode() == "off"


def test_init_rejects_malformed_overlap_env(monkeypatch):
    """hvd.init() fails fast — not the first training step — on a
    malformed knob, like the compression/topology knobs."""
    import horovod_tpu as H

    monkeypatch.setenv(OV.OVERLAP_ENV, "sideways")
    with pytest.raises(ValueError, match="HVD_TPU_OVERLAP"):
        H.init(devices=jax.devices())


def test_auto_resolution_per_mesh_platform(monkeypatch):
    """auto = streaming only on real multi-replica accelerator meshes;
    CPU/virtual meshes keep the monolithic program (their shared thread
    pool has no comm/compute concurrency to exploit)."""
    from types import SimpleNamespace

    monkeypatch.delenv(OV.OVERLAP_ENV, raising=False)
    cpu_mesh = SimpleNamespace(devices=np.asarray(
        [SimpleNamespace(platform="cpu")] * 8))
    tpu_mesh = SimpleNamespace(devices=np.asarray(
        [SimpleNamespace(platform="tpu")] * 8))
    one_tpu = SimpleNamespace(devices=np.asarray(
        [SimpleNamespace(platform="tpu")]))
    assert OV.resolve_mode(None, cpu_mesh) == "off"
    assert OV.resolve_mode(None, tpu_mesh) == "stream"
    assert OV.resolve_mode(None, one_tpu) == "off"  # nothing to reduce
    assert OV.resolve_mode("on", cpu_mesh) == "stream"  # explicit wins
    assert OV.resolve_mode("serial", tpu_mesh) == "serial"
    with pytest.raises(ValueError, match="overlap"):
        OV.resolve_mode("diagonal", cpu_mesh)


def test_overlap_knob_in_hello_env_fingerprint(monkeypatch):
    """HVD_TPU_OVERLAP rides the HELLO env fingerprint: a rank
    diverging on the overlap mode is named at startup like the
    compression/topology knobs."""
    assert "HVD_TPU_OVERLAP" in compression_mod._SPMD_ENV_KNOBS
    monkeypatch.setenv(OV.OVERLAP_ENV, "on")
    fp_on = compression_mod.env_fingerprint()
    monkeypatch.setenv(OV.OVERLAP_ENV, "off")
    fp_off = compression_mod.env_fingerprint()
    assert fp_on != fp_off
