"""Examples as integration tests (≙ the reference's CI patching the MNIST
examples smaller with sed and running them end-to-end under mpirun,
.travis.yml:105-123).  Each example runs as a real subprocess on the
8-virtual-replica CPU platform with env knobs shrinking the workload.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name: str, extra_env=None, args=(), timeout: float = 420.0):
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name), *args],
        env=env, cwd=REPO, capture_output=True, timeout=timeout)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, f"{name} failed:\n{out}"
    return out


@pytest.mark.slow
def test_jax_mnist_example():
    out = _run_example("jax_mnist.py",
                       {"HVD_TPU_EXAMPLE_EPOCHS": "1",
                        "HVD_TPU_EXAMPLE_DATA": "512"})
    assert "replicas=8" in out
    assert "train-set accuracy:" in out
    assert "checkpoint saved" in out


@pytest.mark.slow
def test_word2vec_example():
    out = _run_example("word2vec.py", {"HVD_TPU_EXAMPLE_STEPS": "5"})
    assert "step 0: loss=" in out


@pytest.mark.slow
def test_mnist_callbacks_example():
    # 3 epochs: covers the 2-epoch warmup ramp plus one epoch at full LR.
    out = _run_example("mnist_callbacks.py", {"HVD_TPU_EXAMPLE_EPOCHS": "3"})
    assert "epoch 0:" in out and "epoch 2:" in out


@pytest.mark.slow
def test_pytorch_mnist_example():
    out = _run_example("pytorch_mnist.py", {"HVD_TPU_EXAMPLE_EPOCHS": "2"})
    assert "pytorch_mnist: OK" in out


@pytest.mark.slow
def test_keras_mnist_example():
    out = _run_example("keras_mnist.py", {"HVD_TPU_EXAMPLE_EPOCHS": "2"})
    assert "keras_mnist: OK" in out


@pytest.mark.slow
def test_transformer_lm_example():
    # dp4 x tp2 over the 8 virtual devices; loss must improve.
    out = _run_example("transformer_lm.py",
                       {"HVD_TPU_EXAMPLE_STEPS": "15"})
    assert "transformer_lm: OK" in out


@pytest.mark.slow
def test_transformer_lm_export_then_serve_lm_example(tmp_path):
    """Train → --export → serve_lm one-shot generation, end to end
    through the serving checkpoint (hvd-serve, docs/inference.md)."""
    ckpt = str(tmp_path / "lm-ckpt")
    out = _run_example("transformer_lm.py",
                       {"HVD_TPU_EXAMPLE_STEPS": "5"},
                       args=("--export", ckpt))
    assert "serving checkpoint exported" in out
    assert os.path.exists(os.path.join(ckpt, "params.msgpack"))
    assert os.path.exists(os.path.join(ckpt, "serving.json"))
    out = _run_example("serve_lm.py",
                       args=(ckpt, "--tokens", "5,3,8,1", "-n", "8"))
    assert "serve_lm: OK" in out
    line = [ln for ln in out.splitlines()
            if ln.strip().startswith("{")][0]
    import json

    resp = json.loads(line)
    assert len(resp["tokens"]) == 8
    assert all(0 <= t < 512 for t in resp["tokens"])


@pytest.mark.slow
def test_distill_draft_then_serve_with_draft_example(tmp_path):
    """Train → --export → distill a draft → serve with speculative
    decoding armed (--draft), end to end through the checkpoint pair
    (hvd-spec + hvd-serve, docs/inference.md)."""
    ckpt = str(tmp_path / "lm-ckpt")
    draft = str(tmp_path / "lm-draft")
    out = _run_example("transformer_lm.py",
                       {"HVD_TPU_EXAMPLE_STEPS": "5"},
                       args=("--export", ckpt))
    assert "serving checkpoint exported" in out
    out = _run_example("distill_draft.py",
                       {"HVD_TPU_EXAMPLE_STEPS": "8"},
                       args=(ckpt, "--export", draft))
    assert "draft checkpoint exported" in out
    assert "distill_draft: OK" in out
    assert os.path.exists(os.path.join(draft, "params.msgpack"))
    assert os.path.exists(os.path.join(draft, "serving.json"))
    out = _run_example("serve_lm.py",
                       args=(ckpt, "--draft", draft,
                             "--tokens", "5,3,8,1", "-n", "8"))
    assert "serve_lm: OK" in out
    line = [ln for ln in out.splitlines()
            if ln.strip().startswith("{")][0]
    import json

    resp = json.loads(line)
    assert len(resp["tokens"]) == 8
    assert all(0 <= t < 512 for t in resp["tokens"])


@pytest.mark.slow
def test_resnet50_synthetic_example():
    # Start cold: the example resumes from its fixed checkpoint path.
    ckpt = "/tmp/horovod_tpu_resnet50/ckpt.msgpack"
    if os.path.exists(ckpt):
        os.remove(ckpt)
    out = _run_example("resnet50_synthetic.py", args=("--epochs", "1"))
    assert "epoch 0:" in out
    assert "checkpoint saved" in out
    # Resume the SAME checkpoint through the ZeRO-1 trainer: the
    # params/stats checkpoint is optimizer-layout-agnostic, so plain-DP
    # and sharded-optimizer runs interoperate.
    out = _run_example("resnet50_synthetic.py",
                       args=("--epochs", "2", "--zero"))
    assert "resumed from epoch 1" in out
    assert "epoch 1:" in out
    assert "checkpoint saved" in out
    # And once more through the FSDP trainer: trainer.params' pytree
    # property keeps the same checkpoint interoperable with fully
    # sharded parameter storage.
    out = _run_example("resnet50_synthetic.py",
                       args=("--epochs", "3", "--fsdp"))
    assert "resumed from epoch 2" in out
    assert "epoch 2:" in out
    assert "checkpoint saved" in out


@pytest.mark.slow
def test_uneven_join_example():
    """hvd.join example under the real 2-process launcher: the fast rank
    joins, the slow rank finishes, the last joiner's weights broadcast."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["HVD_TPU_EXAMPLE_STEPS"] = "3"
    # The launcher's children run the script directly (no installed
    # package): put the repo on their import path.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--platform", "cpu", os.path.join(REPO, "examples",
                                           "uneven_join.py")],
        env=env, cwd=REPO, capture_output=True, timeout=300)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out
    assert "uneven_join: OK rank=0" in out
    assert "uneven_join: OK rank=1" in out
    assert "last_joined=1" in out


@pytest.mark.slow
def test_elastic_train_example(tmp_path):
    """Elastic example under the real --elastic launcher: rank 1 dies at
    step 5, the job relaunches and resumes from the last commit."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["HVD_TPU_EXAMPLE_DIE_AT"] = "5"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--platform", "cpu", "--elastic", "--max-restarts", "2",
         "--elastic-dir", str(tmp_path),
         os.path.join(REPO, "examples", "elastic_train.py")],
        env=env, cwd=REPO, capture_output=True, timeout=420)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out
    assert "elastic_train: rank 1 dying at step 5" in out
    assert "[elastic] job failed" in out
    assert "resumed rank=0 from committed step 4" in out
    assert "elastic_train: OK rank=0" in out
    assert "elastic_train: OK rank=1" in out


@pytest.mark.slow
def test_collectives_tour_example():
    """Every collective family self-verified over the real 2-process
    launcher in one run."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--platform", "cpu",
         os.path.join(REPO, "examples", "collectives_tour.py")],
        env=env, cwd=REPO, capture_output=True, timeout=300)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out
    assert "collectives_tour: OK rank=0" in out
    assert "collectives_tour: OK rank=1" in out
