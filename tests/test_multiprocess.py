"""Multi-process integration tests: two REAL processes under
jax.distributed, negotiating over the TCP control plane.

TPU translation of the reference's ``mpirun -np 2 pytest`` CI leg
(.travis.yml:96-123): validation and stall detection fire on genuine
cross-process disagreements, not synthetic in-process injections.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")


def _launch(scenario: str, extra_env=None, timeout: float = 300.0,
            expect_rc0: bool = True, np_: int = 2, launcher_args=()):
    env = dict(os.environ)
    # One CPU device per process (the launcher's conftest-style 8-device
    # override would blur the process==replica mapping this test is about).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         "--platform", "cpu", *launcher_args, WORKER, scenario],
        env=env, cwd=REPO, capture_output=True, timeout=timeout)
    out = proc.stdout.decode() + proc.stderr.decode()
    if expect_rc0:
        assert proc.returncode == 0, f"scenario {scenario} failed:\n{out}"
    return out


@pytest.mark.slow
def test_two_process_scenarios_combined(tmp_path):
    """All NON-DESTRUCTIVE scenarios in ONE launch (suite wall-clock:
    each launch pays full JAX init per rank — round-4 verdict item 7).
    Covers: collectives incl. ragged/sparse/object (basic), cross-rank
    mismatch validation, SPMD training, WITHDRAW fail-fast + recovery,
    hvd.join() on an uneven workload, stall warning naming the late
    rank, checkpoint save/restore/resume, the torch frontend, the
    tf.function bridge, and the timeline recording negotiation — each
    still asserted via its own marker."""
    import json as _json
    import time as _time

    pytest.importorskip("torch")
    pytest.importorskip("tensorflow")
    tl = tmp_path / "timeline.json"
    flight_dir = tmp_path / "flight"
    combo = ("basic,mismatch,spmd_train,metrics,stall,withdraw,join,"
             "checkpoint,torch_frontend,tf_function")
    t0 = _time.monotonic()
    out = _launch("combo", extra_env={
        "HVD_TPU_COMBO": combo,
        "HOROVOD_STALL_WARNING_SECONDS": "1.5",
        "HVD_TPU_TEST_CKPT": str(tmp_path / "ck.msgpack"),
        "HOROVOD_TIMELINE": str(tl),
        "HVD_TPU_FLIGHT_DIR": str(flight_dir),
    }, timeout=600.0)
    for marker in ("BASIC_OK", "MISMATCH_OK", "SPMD_OK", "METRICS_OK",
                   "STALL_OK", "WITHDRAW_OK", "JOIN_OK", "CKPT_OK",
                   "TORCH_OK", "TFFN_OK", "COMBO_OK"):
        assert f"{marker} rank=0" in out, (marker, out)
        assert f"{marker} rank=1" in out, (marker, out)
    # The rank-0 coordinator named the late rank while stalled.
    assert "waiting on replicas: [1]" in out
    # The stall also dumped the flight recorder on rank 0, and the
    # dump's tail names the stalled tensor and the non-ready rank
    # (ISSUE 4 acceptance: the seeded stall in the slow mp leg).
    import glob as _glob

    stall_dumps = sorted(_glob.glob(
        str(flight_dir / "hvd_flight_rank0_*stall*.json")))
    assert stall_dumps, sorted(_glob.glob(str(flight_dir / "*")))
    payload = _json.loads(open(stall_dumps[-1]).read())
    stall_events = [e for e in payload["events"]
                    if e["kind"] == "stall"]
    assert stall_events, payload["events"][-5:]
    assert "late.op" in stall_events[-1]["args"][0]
    assert "waiting on replicas: [1]" in stall_events[-1]["args"][0]
    # The withdraw legs failed fast (well under one 300 s timeout).
    assert _time.monotonic() - t0 < 300.0
    # Timeline recorded negotiation events (rank-0-only writer).
    text = tl.read_text()
    events = _json.loads(text if text.rstrip().endswith("]")
                         else text.rstrip().rstrip(",") + "]")
    names = {e.get("name") for e in events if isinstance(e, dict)}
    assert any("NEGOTIATE" in (n or "") for n in names), sorted(names)[:20]


@pytest.mark.slow
def test_verify_program_divergence_diagnostics():
    """hvd-analyze pass 1 across REAL processes: a matching collective
    program verifies clean over the TCP control plane, and every
    divergence kind — dtype, shape, order, count, process-set deadlock
    cycle — fails at verify time (before any data-plane work) with a
    diagnostic naming the first divergent entry and both ranks'
    records.  One launch covers all cases (tests/mp_worker.py
    scenario_verify)."""
    out = _launch("verify", timeout=300.0)
    for rank in (0, 1):
        assert f"VERIFY_OK rank={rank}" in out, out
        for case in ("dtype", "shape", "order", "count", "cycle"):
            assert f"VERIFY_DIVERGE_OK rank={rank} case={case}" in out, \
                (case, out)
        assert f"VERIFY_ALL_OK rank={rank}" in out, out


@pytest.mark.slow
def test_two_process_cluster_metrics(tmp_path):
    """hvd-telemetry over REAL processes: cluster_metrics() on rank 0
    aggregates both ranks' snapshots over FRAME_METRICS (seeded with
    control-plane-only traffic, so this leg runs under any jax build —
    like the shutdown/verify legs), and the error dumps land in
    HVD_TPU_FLIGHT_DIR on both ranks."""
    import glob as _glob

    flight_dir = tmp_path / "flight"
    out = _launch("metrics", extra_env={
        "HVD_TPU_FLIGHT_DIR": str(flight_dir)}, timeout=300.0)
    assert "METRICS_OK rank=0" in out, out
    assert "METRICS_OK rank=1" in out, out
    # The seeded mismatches dumped the flight ring on both ranks.
    for rank in (0, 1):
        dumps = _glob.glob(
            str(flight_dir / f"hvd_flight_rank{rank}_*error*.json"))
        assert dumps, (rank, sorted(_glob.glob(str(flight_dir / "*"))))


@pytest.mark.slow
def test_two_process_fleet_trace(tmp_path):
    """hvd-trace acceptance over REAL processes (ISSUE 10): rank 1 is
    a seeded slow rank (loader stall before each collective);
    ``hvd.dump_fleet_trace()`` on rank 0 merges both ranks' span
    buffers into ONE clock-corrected trace where same-(step, cycle)
    spans overlap, and the analyzer attributes the stall to rank 1
    with blame ``host`` — deterministically across two replays.  All
    assertions live in tests/mp_worker.py scenario_trace (they run
    where the merged file is); this test gates the markers and that
    the merged artifact exists and parses."""
    import json as _json

    out = tmp_path / "fleet_trace.json"
    log = _launch("trace", extra_env={"HVD_TPU_TRACE_OUT": str(out)},
                  timeout=300.0)
    assert "TRACE_OK rank=0" in log, log
    assert "TRACE_OK rank=1" in log, log
    data = _json.load(open(out))
    assert data["metadata"]["format"] == "hvd-fleet-trace-v1"
    assert data["metadata"]["ranks"] == [0, 1]


@pytest.mark.slow
def test_two_process_shutdown_poisons_peer_pending_op():
    out = _launch("shutdown")
    assert "SHUTDOWN_OK rank=0" in out
    assert "SHUTDOWN_OK rank=1" in out


@pytest.mark.slow
def test_dead_worker_fails_pending_ops_with_rank():
    # A worker dying mid-job still exits the launch nonzero (the jax
    # coordination service reports the dead task at teardown) — correct
    # for a distributed job; the assertions are about the detection.
    # The survivor must exit promptly with its diagnosis rather than
    # blocking in jax's exit barrier (disarm_distributed_shutdown).
    out = _launch("dead_worker", expect_rc0=False, timeout=120.0)
    assert "DEADWORKER_OK rank=0" in out
    assert "terminated unexpectedly" in out  # controller's stderr report


@pytest.mark.slow
def test_dead_worker_all_survivors_diagnose_and_exit():
    # np=3, last rank dies: BOTH survivors — the rank-0 controller and a
    # plain worker — must fail pending ops with the diagnosis and exit
    # promptly (neither may block in jax.distributed's exit barrier,
    # which the dead rank can never reach).
    out = _launch("dead_worker", expect_rc0=False, timeout=120.0, np_=3)
    assert "DEADWORKER_OK rank=0" in out
    assert "DEADWORKER_OK rank=1" in out


@pytest.mark.slow
def test_dead_controller_terminates_workers_promptly():
    # Rank 0 dies — taking the jax coordination service with it. The
    # worker must terminate within seconds, either by jax's client
    # noticing the dead service (the usual winner of the race) or by
    # our transport's controller-death diagnosis. A hang here would
    # block until the 120 s timeout and fail the test.
    import time as _time

    t0 = _time.monotonic()
    out = _launch("dead_controller", expect_rc0=False, timeout=120.0)
    assert _time.monotonic() - t0 < 90.0
    assert ("DEADCTRL_OK rank=1" in out
            or "JAX distributed service detected fatal errors" in out), out


@pytest.mark.slow
def test_clean_exit_without_shutdown_is_cooperative():
    # A worker that simply returns (no hvd.shutdown()) must NOT be
    # diagnosed as crashed: the exit handshake makes it cooperative, both
    # processes keep jax's exit barrier, and the launch exits rc=0.
    out = _launch("clean_exit", timeout=120.0)
    assert "CLEANEXIT_OK rank=0" in out
    assert "CLEANEXIT_OK rank=1" in out
    assert "terminated unexpectedly" not in out


@pytest.mark.slow
def test_process_sets_three_processes():
    """Process sets over REAL processes: subset negotiation via per-set
    coordinators on the controller, sub-mesh execution, collective
    registration, non-member rejection, coexistence with global ops."""
    out = _launch("process_sets", np_=3, timeout=300.0)
    for r in range(3):
        assert f"PSETS_OK rank={r}" in out, out


@pytest.mark.slow
def test_elastic_relaunch_resumes_from_commit(tmp_path):
    """Elastic mode end-to-end: rank 1 dies hard at step 5; the
    --elastic launcher relaunches; the job resumes from the last commit
    (step 4) and converges to the same weights as an uninterrupted run
    (replayed in numpy below)."""
    import re

    import numpy as np

    out = _launch(
        "elastic", timeout=420.0,
        launcher_args=("--elastic", "--max-restarts", "2",
                       "--elastic-dir", str(tmp_path)))
    # The launcher relaunched exactly once.
    assert out.count("[elastic] job failed") == 1, out
    # Both ranks resumed from the step-4 commit, not from scratch.
    assert "ELASTIC_RESUMED rank=0 step=4" in out, out
    assert "ELASTIC_RESUMED rank=1 step=4" in out, out
    assert "ELASTIC_OK rank=0" in out and "ELASTIC_OK rank=1" in out, out

    # Replay the training arithmetic (same seeds, same f32 dtypes): the
    # recovered run must match the uninterrupted result.
    total = 8
    w_true = np.array([1.0, -2.0], dtype="float32")
    data = []
    for r in range(2):
        rng = np.random.RandomState(17 + r)
        X = rng.normal(size=(total, 16, 2)).astype("float32")
        data.append((X, X @ w_true))
    w = np.zeros(2, dtype="float32")
    for i in range(total):
        grads = [2.0 * X[i].T @ (X[i] @ w - y[i]) / X[i].shape[0]
                 for X, y in data]
        w = w - 0.1 * (grads[0] + grads[1]) / 2.0
    got = [
        [float(v) for v in m.group(1).split(",")]
        for m in re.finditer(r"ELASTIC_OK rank=\d w=\[([^\]]+)\]", out)
    ]
    assert len(got) == 2, out
    for g in got:
        np.testing.assert_allclose(g, w, atol=1e-4)


@pytest.mark.slow
def test_np8_fusion_sets_withdraw_race_and_stall():
    """The rich failure semantics at a scale they had never seen
    (round-4 verdict item 3): 8 real processes — a 24-op fusion storm,
    two OVERLAPPING process sets, four ranks racing to withdraw the
    same op, and a stall warning naming all three late ranks."""
    out = _launch("np8", np_=8, timeout=600.0, extra_env={
        "HOROVOD_STALL_WARNING_SECONDS": "1.5",
    })
    for r in range(8):
        assert f"NP8_OK rank={r}" in out, out
    # The controller's stall report named ALL the missing ranks.
    assert "waiting on replicas: [5, 6, 7]" in out, out


@pytest.mark.slow
def test_elastic_survives_two_sequential_deaths(tmp_path):
    """Two incarnation-ending failures in one job: rank 1 dies hard at
    step 3 and (after a relaunch) again at step 7; the launcher
    relaunches twice, each resume starts from the last commit, and the
    final weights match the uninterrupted run (replayed in-process by
    the worker)."""
    out = _launch(
        "elastic2", timeout=600.0,
        launcher_args=("--elastic", "--max-restarts", "3",
                       "--elastic-dir", str(tmp_path)))
    assert out.count("[elastic] job failed") == 2, out
    # Incarnation 2 resumed from the step-2 commit, incarnation 3 from
    # the step-6 commit — on both ranks.
    for r in range(2):
        assert f"ELASTIC2_RESUMED rank={r} step=2" in out, out
        assert f"ELASTIC2_RESUMED rank={r} step=6" in out, out
        assert f"ELASTIC2_OK rank={r}" in out, out


@pytest.mark.slow
def test_chaos_reconnect_mid_training_bitwise(tmp_path):
    """hvd-chaos acceptance (ISSUE 9): rank 1's control-plane
    connection is hard-reset mid-training; the worker reconnects with
    backoff, the session-resume handshake replays the lost frames, and
    the trained weights are BITWISE-identical to the uninterrupted
    arithmetic (asserted inside tests/mp_worker.py scenario_chaos).
    Like every mp data-plane leg this needs a jax with np>1 CPU
    collectives (CI's jax; the container's 0.4.37 cannot)."""
    flight_dir = tmp_path / "flight"
    out = _launch("chaos", timeout=300.0, extra_env={
        "HVD_TPU_FLIGHT_DIR": str(flight_dir)})
    assert "CHAOS_MP_OK rank=0" in out, out
    assert "CHAOS_MP_OK rank=1" in out, out
    # The reconnect really happened (not a silently-intact socket).
    assert "[hvd-reconnect] rank 1: session resumed" in out, out


@pytest.mark.slow
def test_overlap_mp_bucketed_streaming_bitwise():
    """Multi-process bucketed streaming (ISSUE 12 tentpole a): the
    np=2 overlapped step — per-bucket partial cycles over the REAL
    control plane, mp megakernels, take_async apply — is
    bitwise-identical to the monolithic mp step (segmented AND plain
    schedules), and the steady state replays every bucket from the
    response cache with zero new negotiation misses (asserted inside
    tests/mp_worker.py scenario_overlap).  Like every mp data-plane
    leg this needs a jax with np>1 CPU collectives (CI's jax; the
    container's 0.4.37 cannot)."""
    out = _launch("overlap", timeout=300.0)
    for rank in (0, 1):
        assert f"OVERLAP_SEG_OK rank={rank}" in out, out
        assert f"OVERLAP_PLAIN_OK rank={rank}" in out, out
        assert f"OVERLAP_OK rank={rank}" in out, out


@pytest.mark.slow
def test_response_cache_two_processes():
    """Steady-state negotiation bypass across REAL processes
    (ops/cache.py): coalesced bit-vector request frames, compact replay
    broadcasts, and every invalidation hook — a mid-run program change,
    hvd.join(), process-set add/remove, an autotune fusion-threshold
    update — each logging a cache flush while every asserted result
    stays exactly correct on both ranks."""
    import re

    out = _launch("cache", timeout=300.0)
    for rank in (0, 1):
        for marker in ("CACHE_STEADY_OK", "CACHE_CHANGE_OK",
                       "CACHE_JOIN_OK", "CACHE_PSETS_OK",
                       "CACHE_TUNE_OK", "CACHE_OK"):
            assert f"{marker} rank={rank}" in out, (marker, out)
    # Each invalidation hook logged its flush.
    assert "[hvd-cache]" in out, out
    assert "program change" in out, out
    assert "hvd.join()" in out, out
    assert "membership change" in out, out
    assert "fusion plans flushed" in out, out
    # The steady state served from cache on the controller AND the
    # worker replica.
    hits = [int(m.group(1)) for m in
            re.finditer(r"CACHE_STEADY_OK rank=\d hits=(\d+)", out)]
    assert len(hits) == 2 and all(h > 0 for h in hits), (hits, out)


@pytest.mark.slow
def test_response_cache_disabled_identical_results():
    """The same scenario with HVD_TPU_RESPONSE_CACHE=0: every numeric
    assertion is against exact constants, so this leg passing alongside
    the cache-on leg proves identical results cache on/off."""
    out = _launch("cache", extra_env={"HVD_TPU_RESPONSE_CACHE": "0"},
                  timeout=300.0)
    for rank in (0, 1):
        assert f"CACHE_OK rank={rank}" in out, out


# basic/mismatch/spmd_train/stall/withdraw/checkpoint/torch_frontend/
# tf_function (+ timeline) run batched in
# test_two_process_scenarios_combined; only scenarios that END the group
# (shutdown, deaths, clean exit) need their own launch below.
