"""Multi-process integration tests: two REAL processes under
jax.distributed, negotiating over the TCP control plane.

TPU translation of the reference's ``mpirun -np 2 pytest`` CI leg
(.travis.yml:96-123): validation and stall detection fire on genuine
cross-process disagreements, not synthetic in-process injections.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")


def _launch(scenario: str, extra_env=None, timeout: float = 300.0,
            expect_rc0: bool = True, np_: int = 2):
    env = dict(os.environ)
    # One CPU device per process (the launcher's conftest-style 8-device
    # override would blur the process==replica mapping this test is about).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
         "--platform", "cpu", WORKER, scenario],
        env=env, cwd=REPO, capture_output=True, timeout=timeout)
    out = proc.stdout.decode()
    if expect_rc0:
        assert proc.returncode == 0, f"scenario {scenario} failed:\n{out}"
    return out


@pytest.mark.slow
def test_two_process_collectives():
    out = _launch("basic")
    assert "BASIC_OK rank=0" in out
    assert "BASIC_OK rank=1" in out


@pytest.mark.slow
def test_two_process_mismatch_raises_on_both_ranks():
    out = _launch("mismatch")
    assert "MISMATCH_OK rank=0" in out
    assert "MISMATCH_OK rank=1" in out


@pytest.mark.slow
def test_two_process_shutdown_poisons_peer_pending_op():
    out = _launch("shutdown")
    assert "SHUTDOWN_OK rank=0" in out
    assert "SHUTDOWN_OK rank=1" in out


@pytest.mark.slow
def test_two_process_stall_warning_names_missing_rank():
    out = _launch("stall",
                  extra_env={"HOROVOD_STALL_WARNING_SECONDS": "1.5"})
    assert "STALL_OK rank=0" in out
    assert "STALL_OK rank=1" in out
    # The rank-0 coordinator must have named the late rank while waiting.
    assert "waiting on replicas: [1]" in out


@pytest.mark.slow
def test_two_process_torch_frontend():
    # Torch frontend end-to-end across real processes: eager tensor
    # collectives, broadcast_parameters, DistributedOptimizer averaging.
    pytest.importorskip("torch")
    out = _launch("torch_frontend")
    assert "TORCH_OK rank=0" in out
    assert "TORCH_OK rank=1" in out


@pytest.mark.slow
def test_two_process_spmd_training_step():
    # The static fast path (make_train_step) across real processes:
    # identical loss on every rank, and the per-process local-shard
    # input model (shard_local_batch) matches the full-global-array one.
    out = _launch("spmd_train")
    assert "SPMD_OK rank=0" in out
    assert "SPMD_OK rank=1" in out


@pytest.mark.slow
def test_dead_worker_fails_pending_ops_with_rank():
    # A worker dying mid-job still exits the launch nonzero (the jax
    # coordination service reports the dead task at teardown) — correct
    # for a distributed job; the assertions are about the detection.
    # The survivor must exit promptly with its diagnosis rather than
    # blocking in jax's exit barrier (disarm_distributed_shutdown).
    out = _launch("dead_worker", expect_rc0=False, timeout=120.0)
    assert "DEADWORKER_OK rank=0" in out
    assert "terminated unexpectedly" in out  # controller's stderr report


@pytest.mark.slow
def test_dead_worker_all_survivors_diagnose_and_exit():
    # np=3, last rank dies: BOTH survivors — the rank-0 controller and a
    # plain worker — must fail pending ops with the diagnosis and exit
    # promptly (neither may block in jax.distributed's exit barrier,
    # which the dead rank can never reach).
    out = _launch("dead_worker", expect_rc0=False, timeout=120.0, np_=3)
    assert "DEADWORKER_OK rank=0" in out
    assert "DEADWORKER_OK rank=1" in out


@pytest.mark.slow
def test_dead_controller_terminates_workers_promptly():
    # Rank 0 dies — taking the jax coordination service with it. The
    # worker must terminate within seconds, either by jax's client
    # noticing the dead service (the usual winner of the race) or by
    # our transport's controller-death diagnosis. A hang here would
    # block until the 120 s timeout and fail the test.
    import time as _time

    t0 = _time.monotonic()
    out = _launch("dead_controller", expect_rc0=False, timeout=120.0)
    assert _time.monotonic() - t0 < 90.0
    assert ("DEADCTRL_OK rank=1" in out
            or "JAX distributed service detected fatal errors" in out), out


@pytest.mark.slow
def test_clean_exit_without_shutdown_is_cooperative():
    # A worker that simply returns (no hvd.shutdown()) must NOT be
    # diagnosed as crashed: the exit handshake makes it cooperative, both
    # processes keep jax's exit barrier, and the launch exits rc=0.
    out = _launch("clean_exit", timeout=120.0)
    assert "CLEANEXIT_OK rank=0" in out
    assert "CLEANEXIT_OK rank=1" in out
    assert "terminated unexpectedly" not in out


@pytest.mark.slow
def test_two_process_tf_function_bridge():
    # Round-4 verdict item 3: collectives inside tf.function, across two
    # REAL processes — repeated compiled executions and a compiled train
    # step converging on the gradient AVERAGE of divergent ranks.
    pytest.importorskip("tensorflow")
    out = _launch("tf_function", timeout=240.0)
    assert "TFFN_OK rank=0" in out
    assert "TFFN_OK rank=1" in out


@pytest.mark.slow
def test_withdraw_fails_group_fast_and_group_survives():
    # Round-4 verdict item 4: a synchronize timeout on one rank must fail
    # the op on EVERY rank within seconds (WITHDRAW frame -> coordinator
    # ERROR broadcast), and must not poison the group — both legs
    # (worker-initiated and controller-initiated) plus recovery
    # collectives run inside one launch.
    import time as _time

    t0 = _time.monotonic()
    out = _launch("withdraw",
                  extra_env={"HOROVOD_TPU_SYNC_TIMEOUT": "2",
                             "HOROVOD_TPU_WITHDRAW_GRACE": "10"},
                  timeout=180.0)
    assert "WITHDRAW_OK rank=0" in out
    assert "WITHDRAW_OK rank=1" in out
    # Well under one serial 300s timeout, let alone two.
    assert _time.monotonic() - t0 < 120.0


@pytest.mark.slow
def test_two_process_checkpoint_restore_and_resume(tmp_path):
    out = _launch("checkpoint",
                  extra_env={"HVD_TPU_TEST_CKPT": str(tmp_path / "ck.msgpack")})
    assert "CKPT_OK rank=0" in out
    assert "CKPT_OK rank=1" in out


@pytest.mark.slow
def test_two_process_timeline_records_negotiation(tmp_path):
    import json as _json

    tl = tmp_path / "timeline.json"
    out = _launch("basic", extra_env={"HOROVOD_TIMELINE": str(tl)})
    assert "BASIC_OK rank=0" in out
    text = tl.read_text()
    events = _json.loads(text if text.rstrip().endswith("]")
                         else text.rstrip().rstrip(",") + "]")
    names = {e.get("name") for e in events if isinstance(e, dict)}
    assert any("NEGOTIATE" in (n or "") for n in names), sorted(names)[:20]
