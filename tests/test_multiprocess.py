"""Multi-process integration tests: two REAL processes under
jax.distributed, negotiating over the TCP control plane.

TPU translation of the reference's ``mpirun -np 2 pytest`` CI leg
(.travis.yml:96-123): validation and stall detection fire on genuine
cross-process disagreements, not synthetic in-process injections.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")


def _launch(scenario: str, extra_env=None, timeout: float = 300.0,
            expect_rc0: bool = True):
    env = dict(os.environ)
    # One CPU device per process (the launcher's conftest-style 8-device
    # override would blur the process==replica mapping this test is about).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--platform", "cpu", WORKER, scenario],
        env=env, cwd=REPO, capture_output=True, timeout=timeout)
    out = proc.stdout.decode()
    if expect_rc0:
        assert proc.returncode == 0, f"scenario {scenario} failed:\n{out}"
    return out


@pytest.mark.slow
def test_two_process_collectives():
    out = _launch("basic")
    assert "BASIC_OK rank=0" in out
    assert "BASIC_OK rank=1" in out


@pytest.mark.slow
def test_two_process_mismatch_raises_on_both_ranks():
    out = _launch("mismatch")
    assert "MISMATCH_OK rank=0" in out
    assert "MISMATCH_OK rank=1" in out


@pytest.mark.slow
def test_two_process_shutdown_poisons_peer_pending_op():
    out = _launch("shutdown")
    assert "SHUTDOWN_OK rank=0" in out
    assert "SHUTDOWN_OK rank=1" in out


@pytest.mark.slow
def test_two_process_stall_warning_names_missing_rank():
    out = _launch("stall",
                  extra_env={"HOROVOD_STALL_WARNING_SECONDS": "1.5"})
    assert "STALL_OK rank=0" in out
    assert "STALL_OK rank=1" in out
    # The rank-0 coordinator must have named the late rank while waiting.
    assert "waiting on replicas: [1]" in out


@pytest.mark.slow
def test_dead_worker_fails_pending_ops_with_rank():
    # A worker dying mid-job makes the launch exit nonzero (jax's
    # coordination service aborts the survivors at teardown) — correct
    # for a distributed job; the assertions are about the detection.
    out = _launch("dead_worker", expect_rc0=False)
    assert "DEADWORKER_OK rank=0" in out
    assert "terminated unexpectedly" in out  # controller's stderr report


@pytest.mark.slow
def test_two_process_checkpoint_restore_and_resume(tmp_path):
    out = _launch("checkpoint",
                  extra_env={"HVD_TPU_TEST_CKPT": str(tmp_path / "ck.msgpack")})
    assert "CKPT_OK rank=0" in out
    assert "CKPT_OK rank=1" in out


@pytest.mark.slow
def test_two_process_timeline_records_negotiation(tmp_path):
    import json as _json

    tl = tmp_path / "timeline.json"
    out = _launch("basic", extra_env={"HOROVOD_TIMELINE": str(tl)})
    assert "BASIC_OK rank=0" in out
    text = tl.read_text()
    events = _json.loads(text if text.rstrip().endswith("]")
                         else text.rstrip().rstrip(",") + "]")
    names = {e.get("name") for e in events if isinstance(e, dict)}
    assert any("NEGOTIATE" in (n or "") for n in names), sorted(names)[:20]
