"""Elastic training: State commit/restore/sync and the @elastic.run
retry loop (single-process legs; the cross-process relaunch leg lives in
tests/test_multiprocess.py::test_elastic_relaunch_resumes_from_commit).

≙ the post-v0.13 horovod.elastic contract; the v0.13 reference has no
recovery at all (SURVEY.md §5), so all of this is beyond-parity — tested
with the same self-verifying style as the reference's collective tests.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import elastic
from horovod_tpu.ops.collective import HorovodError


def test_state_attribute_roundtrip(hvd):
    s = elastic.State(params={"w": jnp.ones((3,))}, epoch=0)
    assert s.epoch == 0
    s.epoch = 4
    s.extra = "tag"  # values may be added after construction
    assert s.epoch == 4 and s.extra == "tag"
    with pytest.raises(AttributeError):
        _ = s.missing


def test_commit_restore_rolls_back_uncommitted(hvd):
    s = elastic.State(params={"w": jnp.zeros((2,))}, batch=0)
    s.params = {"w": jnp.full((2,), 5.0)}
    s.batch = 7
    s.commit()
    # Diverge past the commit, then roll back.
    s.params = {"w": jnp.full((2,), -1.0)}
    s.batch = 11
    s.transient = 123  # added after the commit: must vanish on restore
    s.restore()
    np.testing.assert_allclose(np.asarray(s.params["w"]), 5.0)
    assert s.batch == 7 and isinstance(s.batch, int)
    assert not hasattr(s, "transient")


def test_restore_before_any_commit_returns_to_construction(hvd):
    s = elastic.State(w=jnp.ones((2,)), epoch=3)
    s.w = jnp.zeros((2,))
    s.epoch = 9
    s.restore()
    np.testing.assert_allclose(np.asarray(s.w), 1.0)
    assert s.epoch == 3


def test_disk_commit_and_fresh_incarnation_sync(hvd, tmp_path, monkeypatch):
    """commit() publishes to HVD_TPU_ELASTIC_DIR; a brand-new State (a
    relaunched incarnation) picks the commit up via sync()."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    s = elastic.State(params={"w": jnp.full((3,), 2.5)}, epoch=6, batch=1)
    s.commit()
    # PR 5: the disk publish is asynchronous; wait_committed() is
    # the durability point.
    assert s.wait_committed(10.0)
    assert (tmp_path / "elastic_state.msgpack").exists()

    fresh = elastic.State(params={"w": jnp.zeros((3,))}, epoch=0, batch=0)
    fresh.sync()
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 2.5)
    assert fresh.epoch == 6 and isinstance(fresh.epoch, int)
    assert fresh.batch == 1
    # sync() establishes the new rollback point.
    fresh.epoch = 99
    fresh.restore()
    assert fresh.epoch == 6


def test_run_retries_rollback_and_reset_callbacks(hvd):
    """A transient HorovodError mid-function: run() rolls back to the
    last commit, fires reset callbacks, and retries — uncommitted
    progress is discarded exactly once."""
    s = elastic.State(w=jnp.zeros((2,)), step=0)
    resets = []
    s.register_reset_callbacks([lambda: resets.append(True)])

    @elastic.run
    def train(state):
        while state.step < 4:
            state.w = state.w + 1.0
            state.step += 1
            if state.step == 3 and not resets:
                # Uncommitted progress (step 3) must be rolled back.
                raise HorovodError("injected transient failure")
            state.commit()
        return "done"

    assert train(s) == "done"
    assert resets == [True]
    assert s.step == 4
    # Steps 1,2 ran once; step 3's first attempt was rolled back, then
    # 3,4 ran after the retry — the committed value is exactly 4 adds.
    np.testing.assert_allclose(np.asarray(s.w), 4.0)


def test_run_exhausts_retries_and_raises(hvd, monkeypatch):
    monkeypatch.setenv("HVD_TPU_ELASTIC_MAX_RETRIES", "2")
    s = elastic.State(step=0)
    attempts = []

    @elastic.run
    def train(state):
        attempts.append(True)
        raise HorovodError("persistent failure")

    with pytest.raises(HorovodError, match="persistent"):
        train(s)
    assert len(attempts) == 3  # initial + 2 retries


def test_run_non_horovod_errors_propagate_immediately(hvd):
    s = elastic.State(step=0)
    attempts = []

    @elastic.run
    def train(state):
        attempts.append(True)
        raise ValueError("user bug")

    with pytest.raises(ValueError):
        train(s)
    assert len(attempts) == 1  # no retry for non-collective failures


def test_run_initial_sync_resumes_from_disk(hvd, tmp_path, monkeypatch):
    """run() syncs before the first attempt, so a relaunched job resumes
    from the previous incarnation's commit without user code."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_DIR", str(tmp_path))
    prev = elastic.State(w=jnp.full((2,), 3.0), step=5)
    prev.commit()

    seen = {}

    @elastic.run
    def train(state):
        seen["step"] = state.step
        seen["w"] = np.asarray(state.w).copy()
        return "ok"

    assert train(elastic.State(w=jnp.zeros((2,)), step=0)) == "ok"
    assert seen["step"] == 5
    np.testing.assert_allclose(seen["w"], 3.0)


def test_commit_snapshot_is_isolated_from_inplace_mutation(hvd):
    """The rollback point must be a fresh buffer: an in-place numpy
    update after commit() (e.g. a torch/numpy optimizer step) must not
    reach back into the snapshot — and post-restore mutation must not
    corrupt it either."""
    w = np.zeros(2, dtype="float32")
    s = elastic.State(w=w)
    s.commit()
    w += 1.0  # in-place: the committed copy must still be zeros
    s.restore()
    np.testing.assert_allclose(np.asarray(s.w), 0.0)
    restored = s.w
    restored += 5.0  # mutate the restored value in place
    s.restore()      # the snapshot must be unaffected
    np.testing.assert_allclose(np.asarray(s.w), 0.0)


def test_retry_budget_resets_after_committed_progress(hvd, monkeypatch):
    """HVD_TPU_ELASTIC_MAX_RETRIES bounds consecutive failures of one
    incident; a long job with committed progress between incidents must
    survive more total failures than the budget."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_MAX_RETRIES", "1")
    s = elastic.State(step=0)
    failures = []

    @elastic.run
    def train(state):
        while state.step < 4:
            state.step += 1
            state.commit()
            # One transient failure after EVERY committed step: 4
            # incidents total, far over the budget of 1 — but each is a
            # fresh incident, so the job must complete.
            if len(failures) < state.step:
                failures.append(state.step)
                raise HorovodError("transient")
        return state.step

    assert train(s) == 4
    assert failures == [1, 2, 3, 4]

    # Without progress, the budget still bounds consecutive failures.
    s2 = elastic.State(step=0)
    tries = []

    @elastic.run
    def never(state):
        tries.append(True)
        raise HorovodError("stuck")

    with pytest.raises(HorovodError):
        never(s2)
    assert len(tries) == 2  # initial + 1 retry


def test_trainer_state_commit_restore_and_retry(hvd):
    """TrainerState binds elastic commit/rollback to a live Trainer
    (≙ the reference-lineage framework State classes): a transient
    failure mid-fit rolls the trainer's params/opt_state back to the
    last commit and the retried run completes."""
    import optax

    from horovod_tpu.frontends.loop import Trainer
    from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                          init_params, synthetic_mnist)

    model = MnistMLP(hidden=16)

    def loss_fn(p, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": p}, images),
                                  labels)

    trainer = Trainer(loss_fn, init_params(model), optax.sgd, lr=0.1)
    images, labels = synthetic_mnist(64)
    batches = lambda e, s: (jnp.asarray(images), jnp.asarray(labels))

    state = elastic.TrainerState(trainer, epoch=0)
    failed = []

    @elastic.run
    def train(state):
        while state.epoch < 3:
            trainer.fit(batches, epochs=state.epoch + 1,
                        steps_per_epoch=2, initial_epoch=state.epoch)
            state.epoch += 1
            state.commit()
            if state.epoch == 2 and not failed:
                # Diverge the live trainer PAST the commit, then fail:
                # the rollback must restore the committed params.
                trainer.params = jax.tree_util.tree_map(
                    lambda x: x * 0.0, trainer.params)
                failed.append(True)
                raise HorovodError("transient")
        return trainer.history

    import jax

    history = train(state)
    assert state.epoch == 3
    assert failed == [True]
    # The zeroed params were rolled back: training continued and the
    # final loss is finite and improved from epoch 0.
    assert history[-1]["loss"] < history[0]["loss"]
    # Committed snapshot round-trips through the trainer property.
    w = np.asarray(jax.tree_util.tree_leaves(trainer.params)[0])
    assert np.isfinite(w).all() and (w != 0).any()
