"""Keras frontend tests (≙ reference test/test_keras.py): the
horovod.keras API surface on Keras 3 + JAX backend."""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import horovod_tpu.frontends.keras as hvdk  # noqa: E402


def _model(lr=0.1, opt=None):
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(1),
    ])
    optimizer = hvdk.DistributedOptimizer(
        opt or keras.optimizers.SGD(learning_rate=lr))
    model.compile(optimizer=optimizer, loss="mse")
    return model


def test_distributed_optimizer_keeps_wrapped_class_name(hvd):
    opt = hvdk.DistributedOptimizer(keras.optimizers.Adam(1e-3))
    assert opt.__class__.__name__ == "Adam"  # restores without horovod
    assert isinstance(opt, keras.optimizers.Adam)


def test_model_fit_trains_under_jit(hvd):
    """model.fit (jitted train step on the JAX backend) through the
    wrapped optimizer: loss must decrease."""
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype("float32")
    y = (x @ rng.randn(4, 1)).astype("float32")
    model = _model(lr=0.05)
    hist = model.fit(x, y, epochs=5, batch_size=16, verbose=0)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0] * 0.7, losses


def test_eager_apply_reduces_gradients(hvd):
    """Custom-loop path: optimizer.apply with concrete per-process grads
    goes through the eager allreduce queue."""
    var = keras.Variable(np.zeros((2,), "float32"))
    opt = hvdk.DistributedOptimizer(keras.optimizers.SGD(learning_rate=1.0),
                                    average=True)
    opt.build([var])
    import jax.numpy as jnp

    opt.apply([jnp.array([1.0, 2.0])], [var])
    # Every replica contributed the same grad; average == grad; SGD(1.0)
    # means var = -grad.
    np.testing.assert_allclose(np.asarray(var), [-1.0, -2.0], rtol=1e-6)


def test_broadcast_global_variables(hvd):
    model = _model()
    before = [np.asarray(v) for v in model.variables]
    hvdk.broadcast_global_variables(model, root_rank=0)
    for b, v in zip(before, model.variables):
        np.testing.assert_allclose(b, np.asarray(v), rtol=1e-6)


def test_broadcast_callback_runs_once(hvd):
    rng = np.random.RandomState(1)
    x = rng.randn(32, 4).astype("float32")
    y = rng.randn(32, 1).astype("float32")
    model = _model()
    cb = hvdk.callbacks.BroadcastGlobalVariablesCallback(0)
    model.fit(x, y, epochs=1, batch_size=16, verbose=0, callbacks=[cb])
    assert cb.broadcast_done


def test_metric_average_callback(hvd):
    cb = hvdk.callbacks.MetricAverageCallback()
    logs = {"loss": 4.0, "acc": 0.5, "name": "not-a-number"}
    cb.on_epoch_end(0, logs)
    # All replicas report the same value; the average is unchanged.
    assert logs["loss"] == pytest.approx(4.0)
    assert logs["acc"] == pytest.approx(0.5)
    assert logs["name"] == "not-a-number"


def test_lr_warmup_callback_ramps_to_initial(hvd):
    rng = np.random.RandomState(2)
    x = rng.randn(64, 4).astype("float32")
    y = rng.randn(64, 1).astype("float32")
    model = _model(lr=0.8)
    warm = hvdk.callbacks.LearningRateWarmupCallback(warmup_epochs=3)
    hist = model.fit(x, y, epochs=4, batch_size=16, verbose=0,
                     callbacks=[warm])
    lrs = hist.history["lr"]
    # Starts near initial/size, ramps upward toward the initial LR.
    assert lrs[0] < lrs[1] < lrs[2]
    assert lrs[0] < 0.8 / 2


def test_lr_schedule_callback_staircase(hvd):
    rng = np.random.RandomState(3)
    x = rng.randn(32, 4).astype("float32")
    y = rng.randn(32, 1).astype("float32")
    model = _model(lr=0.4)
    sched = hvdk.callbacks.LearningRateScheduleCallback(
        multiplier=lambda epoch: 0.1 if epoch >= 2 else 1.0,
        start_epoch=0)
    hist = model.fit(x, y, epochs=4, batch_size=16, verbose=0,
                     callbacks=[sched])
    lrs = hist.history["lr"]
    assert lrs[0] == pytest.approx(0.4, rel=1e-5)
    assert lrs[3] == pytest.approx(0.04, rel=1e-5)


def test_momentum_correction_restores_true_momentum(hvd):
    rng = np.random.RandomState(4)
    x = rng.randn(32, 4).astype("float32")
    y = rng.randn(32, 1).astype("float32")
    model = _model(opt=keras.optimizers.SGD(learning_rate=0.4,
                                            momentum=0.9))
    warm = hvdk.callbacks.LearningRateWarmupCallback(warmup_epochs=2)
    model.fit(x, y, epochs=3, batch_size=16, verbose=0, callbacks=[warm])
    # The true momentum is restored at every epoch end: no drift.
    assert float(np.asarray(model.optimizer.momentum)) == pytest.approx(
        0.9, rel=1e-6)


def test_distributed_optimizer_config_roundtrip(hvd):
    """get_config/from_config survive the dynamic subclass, so a model
    compiled with the wrapper saves and reloads (the reference names the
    subclass after the wrapped optimizer for exactly this)."""
    opt = hvdk.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.3, momentum=0.7))
    cfg = opt.get_config()
    clone = keras.optimizers.SGD.from_config(cfg)  # restores WITHOUT hvd
    assert float(np.asarray(clone.learning_rate)) == pytest.approx(0.3)
    assert clone.momentum == pytest.approx(0.7)
