"""Callback + Trainer + checkpoint tests (≙ reference keras/callbacks.py
semantics and the rank-0/broadcast checkpoint conventions)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu.callbacks as hvd_callbacks
from horovod_tpu.frontends.loop import Trainer
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)
from horovod_tpu.utils.checkpoint import (restore_checkpoint, resume_epoch,
                                          save_checkpoint)


def _make_trainer(hvd, callbacks, lr=0.1, momentum=None, steps=4):
    model = MnistMLP(hidden=16)
    params = init_params(model)

    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)

    kwargs = {"momentum": momentum} if momentum is not None else {}
    return Trainer(loss_fn, params, optimizer_fn=optax.sgd, lr=lr,
                   optimizer_kwargs=kwargs, callbacks=callbacks)


def _batches(images, labels):
    def get(epoch, step):
        return (jnp.asarray(images), jnp.asarray(labels))
    return get


def test_warmup_ramps_lr_from_lr_over_size(hvd):
    """lr starts at ~initial/size and reaches initial after warmup
    (≙ keras/callbacks.py:202-227 math)."""
    lrs = []

    class Spy(hvd_callbacks.Callback):
        def on_batch_begin(self, batch, logs=None):
            lrs.append(self.trainer.lr)

    warmup = hvd_callbacks.LearningRateWarmupCallback(
        warmup_epochs=2, steps_per_epoch=4, momentum_correction=False)
    trainer = _make_trainer(hvd, [warmup, Spy()], lr=0.8)
    images, labels = synthetic_mnist(32)
    trainer.fit(_batches(images, labels), epochs=3, steps_per_epoch=4)

    size = hvd.size()
    # First adjusted batch follows the reference formula exactly
    # (keras/callbacks.py:243-247): epoch' = 0 + 1/steps, multiplier =
    # 1/size * (epoch' * (size-1)/warmup + 1).
    first_epoch = 1.0 / 4
    expected_first = 0.8 / size * (first_epoch * (size - 1) / 2 + 1)
    assert min(lrs) == pytest.approx(expected_first, rel=1e-4)
    assert max(lrs) == pytest.approx(0.8, rel=0.05)
    # After warmup epochs end, lr stays at initial.
    assert lrs[-1] == pytest.approx(0.8, rel=0.05)


def test_schedule_staircase_and_momentum_correction(hvd):
    events = []

    class Spy(hvd_callbacks.Callback):
        def on_batch_begin(self, batch, logs=None):
            events.append((round(self.trainer.lr, 5),
                           round(self.trainer.momentum, 5)))

    sched = hvd_callbacks.LearningRateScheduleCallback(
        multiplier=lambda e: 0.1 if e >= 1 else 1.0, staircase=True,
        momentum_correction=True)
    # Order matters: schedule first so Spy sees the post-adjustment state
    # within the same batch.
    trainer = _make_trainer(hvd, [sched, Spy()], lr=0.5, momentum=0.9)
    images, labels = synthetic_mnist(32)
    trainer.fit(_batches(images, labels), epochs=2, steps_per_epoch=3)

    # Epoch 0: lr 0.5; epoch 1: lr 0.05.
    assert events[0][0] == pytest.approx(0.5)
    assert events[3][0] == pytest.approx(0.05)
    # Momentum corrected by new/old ratio on the adjusting batch, then
    # restored at batch end (the Spy for batch 1 of epoch 1 sees restored).
    assert events[3][1] == pytest.approx(0.9 * 0.1, rel=1e-3)
    assert events[4][1] == pytest.approx(0.9, rel=1e-3)


def test_metric_average_callback(hvd):
    logs = {"loss": 4.0, "acc": 0.5}
    cb = hvd_callbacks.MetricAverageCallback()
    cb.on_epoch_end(0, logs)
    # Replicated values: average across replicas is the identity.
    assert logs["loss"] == pytest.approx(4.0)
    assert logs["acc"] == pytest.approx(0.5)


def test_metric_average_callback_arrays_and_passthrough(hvd):
    """Array-valued metrics average too (the reference averages ANY
    logged value, keras/callbacks.py:37-87 — round-4 verdict weakness
    5); non-numeric logs pass through untouched."""
    per_class = np.array([0.25, 0.5, 0.75], np.float64)
    logs = {"per_class_acc": per_class, "count": 7, "tag": "epoch-0",
            "hist": [1.0, 2.0, 4.0]}
    hvd_callbacks.MetricAverageCallback().on_epoch_end(0, logs)
    np.testing.assert_allclose(logs["per_class_acc"], per_class,
                               rtol=1e-6)
    assert isinstance(logs["per_class_acc"], np.ndarray)
    np.testing.assert_allclose(logs["hist"], [1.0, 2.0, 4.0], rtol=1e-6)
    assert logs["count"] == pytest.approx(7.0)  # ints average as floats
    assert logs["tag"] == "epoch-0"


def test_metric_average_preserves_dtypes(hvd):
    """_average_metric accumulates in promote_types(dtype, float32):
    float64 arrays stay float64 (previously truncated to float32),
    float32 stays float32, int arrays average as float (an averaged
    count is fractional), and int/float scalars keep the historical
    Python-float contract (round-5 verdict weak #6)."""
    from horovod_tpu.callbacks import _average_metric
    from horovod_tpu.ops import collective as C

    f64 = np.linspace(0.0, 1.0, 5, dtype=np.float64)
    out64 = _average_metric(C.allreduce, "m64", f64)
    assert out64.dtype == np.float64, out64.dtype
    np.testing.assert_allclose(out64, f64, rtol=1e-6)

    f32 = np.array([1.5, 2.5], np.float32)
    out32 = _average_metric(C.allreduce, "m32", f32)
    assert out32.dtype == np.float32, out32.dtype
    np.testing.assert_allclose(out32, f32, rtol=1e-6)

    ints = np.array([1, 2, 3], np.int64)
    outi = _average_metric(C.allreduce, "mi", ints)
    assert outi.dtype.kind == "f", outi.dtype  # averaged counts are floats
    np.testing.assert_allclose(outi, [1.0, 2.0, 3.0], rtol=1e-6)

    # Scalars: the historical contract — a Python float, whatever came in.
    assert isinstance(_average_metric(C.allreduce, "si", 7), float)
    assert _average_metric(C.allreduce, "sf", np.float64(2.5)) \
        == pytest.approx(2.5)
    # Non-numeric passes through as None (caller keeps the original).
    assert _average_metric(C.allreduce, "st", "tag") is None


def test_metrics_logger_callback(hvd):
    """MetricsLogger rides telemetry values into the epoch logs under
    the configured prefix; histograms log their count."""
    import horovod_tpu as hvd_mod

    hvd_mod.allreduce(np.ones((2,), np.float32), average=False,
                      name="mlog.op")
    logs = {}
    hvd_callbacks.MetricsLogger().on_epoch_end(0, logs)
    assert logs["hvd/collective.submitted"] >= 1, logs
    assert logs["hvd/collective.completed"] >= 1, logs

    logs_all = {}
    hvd_callbacks.MetricsLogger(
        metrics=["collective.negotiate_seconds"], prefix="t/"
    ).on_epoch_end(0, logs_all)
    assert logs_all["t/collective.negotiate_seconds"] >= 1, logs_all


def test_broadcast_callback_runs(hvd):
    cb = hvd_callbacks.BroadcastGlobalVariablesCallback(0)
    trainer = _make_trainer(hvd, [cb], lr=0.05)
    images, labels = synthetic_mnist(32)
    hist = trainer.fit(_batches(images, labels), epochs=1, steps_per_epoch=2)
    assert len(hist) == 1 and np.isfinite(hist[0]["loss"])


def test_training_with_warmup_still_learns(hvd):
    warmup = hvd_callbacks.LearningRateWarmupCallback(
        warmup_epochs=1, steps_per_epoch=8)
    trainer = _make_trainer(hvd, [warmup], lr=0.5, momentum=0.9)
    images, labels = synthetic_mnist(128)
    hist = trainer.fit(_batches(images, labels), epochs=4, steps_per_epoch=8)
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_checkpoint_roundtrip_and_resume(hvd, tmp_path):
    params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
    path = str(tmp_path / "ckpt.msgpack")
    write = save_checkpoint(path, params, step=7)
    assert write  # truthy on the saving process (PR 5: a handle)
    assert write.wait(10.0)
    target = {"w": jnp.zeros((2, 3)), "b": jnp.zeros(3)}
    restored = restore_checkpoint(path, target)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert resume_epoch(path) == 7


def test_warmup_then_decay_schedule_segments(hvd):
    """The optax-schedule variant: base LR holds between warmup end and the
    first decay epoch; decays land at their own epochs."""
    from horovod_tpu.callbacks import warmup_then_decay_schedule

    spe = 10
    sched = warmup_then_decay_schedule(
        base_lr=1.0, warmup_epochs=2, steps_per_epoch=spe,
        decay_epochs_and_factors=[(5, 0.1), (8, 0.01)])
    size = __import__("horovod_tpu").size()
    assert float(sched(0)) == pytest.approx(1.0 / size)
    assert float(sched(2 * spe)) == pytest.approx(1.0)       # warmup done
    assert float(sched(4 * spe)) == pytest.approx(1.0)       # still base
    assert float(sched(5 * spe)) == pytest.approx(0.1)       # first decay
    assert float(sched(8 * spe)) == pytest.approx(0.01)      # second decay


def test_restore_checkpoint_before_init(tmp_path):
    """Loading a checkpoint before init() must work locally (no broadcast),
    e.g. to build params before bringing up the mesh."""
    import horovod_tpu as hvd

    path = str(tmp_path / "pre_init.msgpack")
    params = {"w": jnp.arange(4.0), "b": jnp.zeros(2)}
    hvd.init(devices=jax.devices())
    write = save_checkpoint(path, params)
    assert write and write.wait(10.0)
    hvd.shutdown()
    assert not hvd.is_initialized()
    target = {"w": jnp.zeros(4), "b": jnp.ones(2)}
    restored = restore_checkpoint(path, target)
    assert jnp.allclose(restored["w"], params["w"])
    assert jnp.allclose(restored["b"], params["b"])
