"""Cooperative-shutdown protocol tests (≙ reference
operations.cc:1377-1474: pending callbacks flushed with SHUT_DOWN_ERROR,
subsequent ops refused)."""

import jax.numpy as jnp
import pytest


def test_shutdown_poisons_pending_async_op(hvd):
    import horovod_tpu as H

    st = H.core.state.global_state()
    # Freeze the background tick so the op stays queued (un-launched).
    st.bg_stop.set()
    st.bg_thread.join(timeout=2.0)
    handle = H.allreduce_async(jnp.ones(4), name="pending.at.shutdown")
    H.shutdown()
    with pytest.raises(H.HorovodError, match="shut down"):
        H.synchronize(handle)


def test_ops_after_peer_shutdown_raise(hvd):
    import horovod_tpu as H

    st = H.core.state.global_state()
    st.peer_shutdown = True  # what a SHUTDOWN response sets
    with pytest.raises(H.HorovodError, match="shut down"):
        H.allreduce(jnp.ones(2))


def test_completed_op_survives_shutdown(hvd):
    """Launched collectives belong to XLA; shutdown must not invalidate
    their handles (only *pending* ones are poisoned)."""
    import horovod_tpu as H

    n = hvd.size()
    handle = H.allreduce_async(jnp.ones(3), average=False, name="done.op")
    out = H.synchronize(handle)
    H.shutdown()
    assert float(out[0]) == float(n)
