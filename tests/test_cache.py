"""Response-cache unit tests (ops/cache.py): key exactness, hit/replay
through the Coordinator facade, memoized fusion plans, every
invalidation hook (program change, join, process-set membership,
autotune threshold, withdraw, capacity), the coalesced wire fast path
over real sockets, and single-process end-to-end numerical identity
cache on vs off."""

import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.ops import cache as hvd_cache
from horovod_tpu.ops import wire
from horovod_tpu.ops.cache import ResponseCache, plan_fusion, request_key
from horovod_tpu.ops.coordinator import Coordinator
from horovod_tpu.ops.wire import (DataType, ReduceOp, Request, RequestType,
                                  Response, ResponseType)

THRESHOLD = 1 << 20


def _req(rank, name, shape=(4,), op=RequestType.ALLREDUCE,
         dtype=DataType.FLOAT32, root=-1, device=-1,
         red=ReduceOp.SUM, psid=0, splits=()):
    return Request(rank, op, dtype, name, root, device, shape, red, psid,
                   splits)


def _tick(coord, cache):
    """One controller drain tick, exactly as ops/collective._drain
    sequences it: marker, replay, fresh negotiation, observation."""
    resps = []
    marker = cache.take_flush_marker()
    if marker is not None:
        resps.append(marker)
    replayed, groups, epoch, compact = cache.take_ready(
        lambda psid: THRESHOLD)
    resps += replayed
    negotiated = coord.poll_responses({})
    resps += negotiated
    for r in resps:
        cache.observe_response(r)
    return resps, replayed, negotiated


# ---------------------------------------------------------------------------
# Key exactness (the digest-collision satellite)
# ---------------------------------------------------------------------------

def test_request_key_same_name_different_shape_never_collides():
    a = request_key(_req(0, "t", shape=(4,)))
    b = request_key(_req(0, "t", shape=(8,)))
    c = request_key(_req(0, "t", shape=(4, 1)))
    assert len({a, b, c}) == 3


def test_request_key_covers_every_negotiated_field():
    base = _req(0, "t")
    variants = [
        _req(1, "t"),                                  # rank
        _req(0, "t", op=RequestType.ALLGATHER),        # op
        _req(0, "t", dtype=DataType.INT32),            # dtype
        _req(0, "t", root=1),                          # root
        _req(0, "t", device=3),                        # device
        _req(0, "t", red=ReduceOp.MAX),                # reduce op
        _req(0, "t", psid=2),                          # process set
        _req(0, "t", op=RequestType.ALLTOALL,
             splits=(2, 2)),                           # splits
    ]
    keys = {request_key(base)} | {request_key(v) for v in variants}
    assert len(keys) == len(variants) + 1


def test_signature_reuses_program_machinery():
    sig = hvd_cache.signature_of(_req(0, "grad.0", red=ReduceOp.AVERAGE))
    assert sig.name == "grad.0" and sig.reduce_op == "average"
    digest = hvd_cache.cycle_digest([sig])
    assert len(digest) == 64  # sha256 hex, same scheme as verify_program


# ---------------------------------------------------------------------------
# plan_fusion (shared by PyCoordinator and the cache replay)
# ---------------------------------------------------------------------------

def test_plan_fusion_groups_like_the_reference():
    def meta(rt=ResponseType.ALLREDUCE, red=ReduceOp.SUM, psid=0,
             dtype=DataType.FLOAT32, nbytes=16, devices=(0,)):
        return hvd_cache._FusionMeta(rt, tuple(devices), red, psid, dtype,
                                     nbytes)

    metas = [
        meta(),                              # 0: fuses with 2
        meta(dtype=DataType.INT32),          # 1: dtype splits
        meta(),                              # 2
        meta(red=ReduceOp.ADASUM),           # 3: adasum never fuses
        meta(rt=ResponseType.ALLGATHER),     # 4: only allreduce fuses
    ]
    groups = plan_fusion(metas, lambda psid: 1024)
    assert groups == [[0, 2], [1], [3], [4]]
    # Threshold exhaustion: 60 + 60 > 100, 60 + 30 fits.
    metas = [meta(nbytes=60), meta(nbytes=60), meta(nbytes=30)]
    assert plan_fusion(metas, lambda psid: 100) == [[0, 2], [1]]


# ---------------------------------------------------------------------------
# Hit / replay through the Coordinator facade
# ---------------------------------------------------------------------------

def _negotiate_program(coord, cache, step):
    """Submit the same 3-tensor program (2 fusable allreduces + one
    allgather) for both ranks; returns the tick's responses."""
    for name in ("a", "b"):
        for r in range(2):
            coord.submit(_req(r, name))
    for r in range(2):
        coord.submit(_req(r, "g", shape=(2, 3), op=RequestType.ALLGATHER))
    return _tick(coord, cache)


def test_cache_hit_skips_negotiation_and_replays_fused():
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    resps0, replayed0, negotiated0 = _negotiate_program(coord, cache, 0)
    assert not replayed0 and len(negotiated0) == 2  # fused a+b, g
    assert cache.live_entries() == 3
    assert cache.stats.hits == 0

    resps1, replayed1, negotiated1 = _negotiate_program(coord, cache, 1)
    # Every request hit; nothing reached the impl.
    assert cache.stats.hits == 6
    assert negotiated1 == []
    assert len(replayed1) == 2
    by_type = {r.response_type: r for r in replayed1}
    assert sorted(by_type[ResponseType.ALLREDUCE].tensor_names) == ["a", "b"]
    assert by_type[ResponseType.ALLGATHER].tensor_names == ["g"]
    # The replayed allgather carries the negotiated per-rank extents.
    assert by_type[ResponseType.ALLGATHER].tensor_sizes == [2, 2]
    assert cache.stats.plan_misses == 1

    _, replayed2, negotiated2 = _negotiate_program(coord, cache, 2)
    assert negotiated2 == [] and len(replayed2) == 2
    assert cache.stats.plan_hits == 1  # memoized packing plan
    coord.close()


def test_program_change_flushes_and_surfaces_mismatch(capfd):
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    for r in range(2):
        coord.submit(_req(r, "t"))
    _tick(coord, cache)
    assert cache.live_entries() == 1

    # Rank 0 hits the cached cycle, then rank 1 shows up with a NEW
    # shape for the same name: the cache must flush, rank 0's cached
    # submission must downgrade into the real table, and the normal
    # cross-rank validation must report the mismatch.
    coord.submit(_req(0, "t"))
    coord.submit(_req(1, "t", shape=(8,)))
    resps, replayed, negotiated = _tick(coord, cache)
    assert replayed == []
    errs = [r for r in resps if r.response_type == ResponseType.ERROR]
    assert len(errs) == 1
    assert "Mismatched allreduce tensor shapes" in errs[0].error_message
    assert cache.live_entries() == 0
    assert cache.stats.downgrades == 0  # in-process conflict, not wire
    err = capfd.readouterr().err
    assert "[hvd-cache]" in err and "program change" in err

    # The group recovers: the new agreeing program negotiates and
    # re-populates the cache.
    for r in range(2):
        coord.submit(_req(r, "t", shape=(8,)))
    _, _, negotiated = _tick(coord, cache)
    assert len(negotiated) == 1
    assert negotiated[0].response_type == ResponseType.ALLREDUCE
    assert cache.live_entries() == 1
    coord.close()


def test_join_disarms_insertion_until_release(capfd):
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    for r in range(2):
        coord.submit(_req(r, "warm"))
    _tick(coord, cache)
    assert cache.live_entries() == 1

    # Rank 0 joins: flush + disarm; a tensor completed via the join must
    # NOT become an entry (the joined rank never sent a request for it).
    coord.submit(Request(0, RequestType.JOIN, DataType.UINT8, "hvd.join"))
    assert "hvd.join" in capfd.readouterr().err
    coord.submit(_req(1, "through.join"))
    resps, _, negotiated = _tick(coord, cache)
    assert any(r.response_type == ResponseType.CACHE_FLUSH for r in resps)
    assert any(r.response_type == ResponseType.ALLREDUCE
               for r in negotiated)
    assert cache.live_entries() == 0

    # Rank 1 joins too: the JOIN release rides the stream and re-arms.
    coord.submit(Request(1, RequestType.JOIN, DataType.UINT8, "hvd.join"))
    resps, _, _ = _tick(coord, cache)
    assert any(r.response_type == ResponseType.JOIN for r in resps)
    for r in range(2):
        coord.submit(_req(r, "post.join"))
    _tick(coord, cache)
    assert cache.live_entries() == 1  # insertion armed again
    coord.close()


def test_membership_allgather_flushes_deterministically():
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    for r in range(2):
        coord.submit(_req(r, "warm"))
    _tick(coord, cache)
    assert cache.live_entries() == 1
    # The registration allgather of add_process_set/remove_process_set:
    # observing it flushes every replica at the same stream position.
    for r in range(2):
        coord.submit(_req(r, "process_set.register.7.sizes", shape=(1,),
                          op=RequestType.ALLGATHER, dtype=DataType.INT64))
    _tick(coord, cache)
    assert cache.live_entries() == 0
    coord.close()


def test_autotune_threshold_change_flushes_plans(capfd):
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    _negotiate_program(coord, cache, 0)
    _negotiate_program(coord, cache, 1)  # builds + memoizes a plan
    assert cache.stats.plan_misses == 1
    coord.set_fusion_threshold(123456)
    err = capfd.readouterr().err
    assert "fusion plans flushed" in err and "123456" in err
    # Entries survive — only the packing decision is recomputed.
    assert cache.live_entries() == 3
    _negotiate_program(coord, cache, 2)
    assert cache.stats.plan_misses == 2
    coord.close()


def test_withdraw_flushes_and_still_fails_group_wide():
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    for r in range(2):
        coord.submit(_req(r, "w"))
    _tick(coord, cache)
    # Rank 0 hits the cached cycle; rank 1 never shows up and rank 0
    # withdraws: the cached submission must downgrade so the standard
    # abandonment ERROR still reaches everyone.
    coord.submit(_req(0, "w"))
    coord.withdraw("w", 0)
    resps, replayed, _ = _tick(coord, cache)
    assert replayed == []
    errs = [r for r in resps if r.response_type == ResponseType.ERROR]
    assert len(errs) == 1
    assert "was abandoned: rank 0" in errs[0].error_message
    assert cache.live_entries() == 0
    coord.close()


def test_capacity_flush_is_marker_driven():
    cache = ResponseCache(rank=0, capacity=2)
    coord = Coordinator(size=1, fusion_threshold=0, cache=cache)
    for name in ("a", "b", "c"):
        coord.submit(_req(0, name))
    _tick(coord, cache)
    assert cache.live_entries() == 3  # over capacity until the check
    orphans = cache.check_capacity()
    assert orphans == []
    assert cache.live_entries() == 0
    marker = cache.take_flush_marker()
    assert marker is not None
    assert marker.response_type == ResponseType.CACHE_FLUSH
    assert marker.tensor_sizes[0] == cache.epoch
    coord.close()


def test_stale_epoch_bit_downgrades_to_real_submit():
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD, cache=cache)
    for r in range(2):
        coord.submit(_req(r, "t"))
    _tick(coord, cache)
    old_epoch = cache.epoch
    cache.flush("test-induced", broadcast=True)
    # A worker bit that raced the flush: tagged with the retired epoch.
    down = cache.hit_from_wire(0, 1, old_epoch)
    assert down is not None and down.tensor_name == "t"
    assert cache.stats.downgrades == 1
    # Resolving it through the real path completes with rank 0's own
    # (also downgraded — via conflictless miss) submission.
    coord.submit(down)
    coord.submit(_req(0, "t"))
    resps, replayed, negotiated = _tick(coord, cache)
    assert replayed == []
    kinds = [r.response_type for r in resps]
    assert ResponseType.ALLREDUCE in kinds
    coord.close()


# ---------------------------------------------------------------------------
# The coalesced wire fast path over real sockets (no XLA involved)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(os.environ.get("HVD_TPU_NO_SOCKETS") == "1",
                    reason="sandbox without loopback sockets")
def test_two_rank_wire_fast_path_bits_and_compact_replay():
    from horovod_tpu.ops import transport as T

    ctrl_cache = ResponseCache(rank=0)
    coord = Coordinator(size=2, fusion_threshold=THRESHOLD,
                        cache=ctrl_cache)
    holder = {}

    def build_controller():
        holder["ctrl"] = T.ControllerTransport(coord, 2, 0)

    # ControllerTransport blocks for the worker HELLO; find its port
    # after bind via the server socket.
    t = threading.Thread(target=build_controller, daemon=True)
    # Use an explicit free port: bind a throwaway socket first.
    import socket as _socket

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    def build_controller_on_port():
        holder["ctrl"] = T.ControllerTransport(coord, 2, port)

    t = threading.Thread(target=build_controller_on_port, daemon=True)
    t.start()
    time.sleep(0.1)
    worker = T.WorkerTransport("127.0.0.1", port, 1)
    wrk_cache = ResponseCache(rank=1)
    worker.cache = wrk_cache
    t.join(timeout=10.0)
    ctrl = holder["ctrl"]
    ctrl.cache = ctrl_cache

    try:
        def controller_tick():
            resps = []
            marker = ctrl_cache.take_flush_marker()
            if marker is not None:
                resps.append(marker)
            replayed, groups, epoch, compact = ctrl_cache.take_ready(
                lambda psid: THRESHOLD)
            resps += replayed
            negotiated = coord.poll_responses({})
            resps += negotiated
            n_other = (1 if marker else 0) + len(negotiated)
            if resps:
                if compact and groups and n_other == 0:
                    ctrl.broadcast_replay(groups, epoch)
                else:
                    ctrl.broadcast_responses(resps)
            for r in resps:
                ctrl_cache.observe_response(r)
            return resps

        def worker_recv(deadline=5.0):
            end = time.monotonic() + deadline
            while time.monotonic() < end:
                got = worker.poll_responses()
                if got is not None:
                    return got
                time.sleep(0.005)
            raise AssertionError("worker never received the broadcast")

        def cycle(names=("x", "y")):
            wreqs = {}
            for name in names:
                req = _req(1, name)
                wreqs[name] = req
                worker.submit(req)
            worker.flush_requests()
            for name in names:
                ctrl.submit(_req(0, name))
            # Tick until EVERY name's negotiation completed: the
            # controller's receive thread may be mid-batch when a tick
            # polls, legally splitting one cycle's responses across two
            # ticks/broadcasts (the protocol delivers both; only this
            # test's bookkeeping must not stop at the first).
            deadline = time.monotonic() + 5.0
            resps = []
            want = {n for n in names}
            while time.monotonic() < deadline:
                resps += controller_tick()
                seen = {n for r in resps for n in r.tensor_names}
                if want <= seen:
                    break
                time.sleep(0.005)
            assert resps, "controller tick produced nothing"
            got = []
            end = time.monotonic() + 5.0
            while time.monotonic() < end:
                batch = worker.poll_responses()
                if batch is not None:
                    got += batch
                    for r in batch:
                        wrk_cache.observe_response(r, own_requests={
                            1: wreqs})
                    if want <= {n for r in got for n in r.tensor_names}:
                        break
                time.sleep(0.005)
            assert got, "worker never received the broadcast"
            return resps, got

        # Cycle 1: cold — full requests, negotiated responses, replicas
        # populated identically on both sides.
        resps1, got1 = cycle()
        assert wrk_cache.live_entries() == ctrl_cache.live_entries() == 2
        assert wrk_cache.stats.hits == 0

        # Cycle 2: steady state — the worker ships ONE coalesced frame
        # of bits, the controller replays from cache and broadcasts the
        # compact entry-index frame, and the worker reconstitutes the
        # identical fused response.
        resps2, got2 = cycle()
        assert wrk_cache.stats.hits == 2
        assert ctrl_cache.stats.replayed_tensors == 2
        assert [sorted(r.tensor_names) for r in got2] == \
            [sorted(r.tensor_names) for r in resps2]
        assert got2[0].response_type == ResponseType.ALLREDUCE
        assert got2[0].tensor_type == resps2[0].tensor_type

        # Flush marker ride-along: a controller-side flush reaches the
        # worker through the stream and resets its replica too.
        ctrl_cache.flush("test-induced", broadcast=True)
        resps3 = controller_tick()
        assert any(r.response_type == ResponseType.CACHE_FLUSH
                   for r in resps3)
        got3 = worker_recv()
        for r in got3:
            wrk_cache.observe_response(r)
        assert wrk_cache.live_entries() == 0
        assert wrk_cache.epoch == ctrl_cache.epoch
    finally:
        worker.close()
        ctrl.close()


# ---------------------------------------------------------------------------
# Single-process end-to-end: numerical identity cache on vs off
# ---------------------------------------------------------------------------

def _run_program():
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    outs = []
    for step in range(3):
        for i in range(3):
            outs.append(np.asarray(hvd.allreduce(
                jnp.full((4,), float(i + 1)), average=False,
                name=f"id.grad.{i}")))
        outs.append(np.asarray(hvd.allgather(
            jnp.ones((2, 2)), name="id.gather")))
        outs.append(np.asarray(hvd.broadcast(
            jnp.arange(3.0), 0, name="id.bcast")))
    from horovod_tpu.core import state as _st

    stats = None
    if _st.global_state().response_cache is not None:
        stats = _st.global_state().response_cache.stats
        stats = (stats.hits, stats.replayed_tensors)
    hvd.shutdown()
    return outs, stats


def test_numerical_identity_cache_on_vs_off(monkeypatch):
    monkeypatch.delenv("HVD_TPU_RESPONSE_CACHE", raising=False)
    on, stats = _run_program()
    assert stats is not None and stats[0] > 0 and stats[1] > 0, stats
    monkeypatch.setenv("HVD_TPU_RESPONSE_CACHE", "0")
    off, stats_off = _run_program()
    assert stats_off is None
    assert len(on) == len(off)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


def test_handle_and_timeline_surface_cache_hits(tmp_path, monkeypatch):
    import json

    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.core import state as _st

    monkeypatch.delenv("HVD_TPU_RESPONSE_CACHE", raising=False)
    monkeypatch.setenv("HOROVOD_TIMELINE", str(tmp_path / "tl.json"))
    hvd.init()
    try:
        h1 = hvd.allreduce_async(jnp.ones((2,)), average=False,
                                 name="tl.op")
        assert not _st.global_state().handle_manager._get(h1).cache_hit
        hvd.synchronize(h1)
        h2 = hvd.allreduce_async(jnp.ones((2,)), average=False,
                                 name="tl.op")
        assert _st.global_state().handle_manager._get(h2).cache_hit
        hvd.synchronize(h2)
    finally:
        hvd.shutdown()
    text = (tmp_path / "tl.json").read_text()
    events = json.loads(text if text.rstrip().endswith("]")
                        else text.rstrip().rstrip(",") + "]")
    names = [e.get("name") for e in events if isinstance(e, dict)]
    assert "CACHE_MISS" in names and "CACHE_HIT" in names
    assert "response_cache" in names  # the hit/miss counter track
    phases = {e.get("args", {}).get("phase") for e in events
              if isinstance(e, dict) and isinstance(e.get("args"), dict)}
    assert {"NEGOTIATE", "EXECUTE"} <= phases
    cache_args = {e["args"].get("cache") for e in events
                  if isinstance(e, dict) and isinstance(e.get("args"), dict)
                  and "cache" in e.get("args", {})}
    assert {"hit", "miss"} <= cache_args


# ---------------------------------------------------------------------------
# The _impl_dirty lost-wakeup regression (the roaming stall flake)
# ---------------------------------------------------------------------------

def test_drain_after_tick_races_mid_submit_still_sees_negotiation():
    """Regression for the roaming single-process stall HorovodError: the
    5 ms background tick landing BETWEEN a submit's dirty-flag update and
    the impl-table insert must not hide the landed request behind the
    cache fast path.  With the old flag-before-submit ordering the tick
    cleared ``_impl_dirty`` and polled still-empty tables, so the one
    explicit drain in ``synchronize`` short-circuited and raised
    "it would stall".  The flag is now set after the impl call, so either
    the racing tick polls the landed request or the flag survives for the
    next drain."""
    cache = ResponseCache(rank=0)
    coord = Coordinator(size=1, fusion_threshold=THRESHOLD, cache=cache)
    inner = coord._impl

    class MidSubmitTick:
        """Impl proxy firing one background drain tick at the exact
        point the real race interleaves it: after the facade's submit
        bookkeeping, before the request lands in the impl tables."""

        def submit(self, req):
            coord.poll_responses({})
            return inner.submit(req)

        def __getattr__(self, name):
            return getattr(inner, name)

    coord._impl = MidSubmitTick()
    assert coord.submit(_req(0, "lostwakeup.t")) is True
    # The single explicit drain that synchronize() performs: it must
    # reach the impl (not the steady-state short circuit) and return
    # the completed negotiation.
    negotiated = coord.poll_responses({"lostwakeup.t": 16})
    assert [r.response_type for r in negotiated] == [ResponseType.ALLREDUCE]
    assert negotiated[0].tensor_names == ["lostwakeup.t"]
    coord.close()
