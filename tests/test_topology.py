"""Multi-axis mesh topology tests (horovod_tpu.core.topology)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core import topology as T


def test_make_mesh_axis_order_and_sizes():
    mesh = T.make_mesh(data=2, model=2, seq=2)
    sizes = T.mesh_axis_sizes(mesh)
    assert sizes[T.DATA_AXIS] == 2
    assert sizes[T.MODEL_AXIS] == 2
    assert sizes[T.SEQ_AXIS] == 2
    assert sizes[T.PIPE_AXIS] == 1
    # data outermost, model innermost
    assert mesh.axis_names[0] == T.DATA_AXIS
    assert mesh.axis_names[-1] == T.MODEL_AXIS


def test_make_mesh_with_config_and_expert_axis():
    cfg = T.ParallelConfig(data=2, expert=2, model=2)
    mesh = T.make_mesh(cfg)
    assert T.mesh_axis_sizes(mesh)[T.EXPERT_AXIS] == 2
    # expert defaults to riding the data axis (no separate axis)
    mesh2 = T.make_mesh(data=8)
    assert T.EXPERT_AXIS not in mesh2.axis_names


def test_make_mesh_device_count_mismatch():
    with pytest.raises(ValueError, match="needs 16 devices"):
        T.make_mesh(data=4, model=4)


def test_make_mesh_rejects_config_plus_kwargs():
    with pytest.raises(TypeError):
        T.make_mesh(T.ParallelConfig(data=8), model=2)


def test_axis_helpers_inside_shard_map():
    mesh = T.make_mesh(data=4, model=2)

    def f(x):
        return (x
                + T.axis_size(T.MODEL_AXIS)
                + T.axis_index(T.DATA_AXIS))[None]

    out = jax.shard_map(
        f, mesh=mesh, in_specs=P(),
        out_specs=P((T.DATA_AXIS, T.PIPE_AXIS, T.SEQ_AXIS, T.MODEL_AXIS)),
        check_vma=False)(jnp.zeros(()))
    # data index contributes 0..3 twice (model axis size 2 everywhere)
    assert sorted(int(v) for v in out) == [2, 2, 3, 3, 4, 4, 5, 5]


def test_validate_mesh():
    mesh = T.make_mesh(data=8)
    with pytest.raises(ValueError, match="missing required"):
        T.validate_mesh(mesh, (T.EXPERT_AXIS,))
    T.validate_mesh(mesh, (T.DATA_AXIS, T.MODEL_AXIS))
