"""Multi-axis mesh topology tests (horovod_tpu.core.topology)."""

import jax
from horovod_tpu.core import compat as _compat
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.core import topology as T


def test_make_mesh_axis_order_and_sizes():
    mesh = T.make_mesh(data=2, model=2, seq=2)
    sizes = T.mesh_axis_sizes(mesh)
    assert sizes[T.DATA_AXIS] == 2
    assert sizes[T.MODEL_AXIS] == 2
    assert sizes[T.SEQ_AXIS] == 2
    assert sizes[T.PIPE_AXIS] == 1
    # data outermost, model innermost
    assert mesh.axis_names[0] == T.DATA_AXIS
    assert mesh.axis_names[-1] == T.MODEL_AXIS


def test_make_mesh_with_config_and_expert_axis():
    cfg = T.ParallelConfig(data=2, expert=2, model=2)
    mesh = T.make_mesh(cfg)
    assert T.mesh_axis_sizes(mesh)[T.EXPERT_AXIS] == 2
    # expert defaults to riding the data axis (no separate axis)
    mesh2 = T.make_mesh(data=8)
    assert T.EXPERT_AXIS not in mesh2.axis_names


def test_make_mesh_device_count_mismatch():
    with pytest.raises(ValueError, match="needs 16 devices"):
        T.make_mesh(data=4, model=4)


def test_make_mesh_rejects_config_plus_kwargs():
    with pytest.raises(TypeError):
        T.make_mesh(T.ParallelConfig(data=8), model=2)


def test_axis_helpers_inside_shard_map():
    mesh = T.make_mesh(data=4, model=2)

    def f(x):
        return (x
                + T.axis_size(T.MODEL_AXIS)
                + T.axis_index(T.DATA_AXIS))[None]

    out = _compat.shard_map(
        f, mesh=mesh, in_specs=P(),
        out_specs=P((T.DATA_AXIS, T.PIPE_AXIS, T.SEQ_AXIS, T.MODEL_AXIS)),
        check_vma=False)(jnp.zeros(()))
    # data index contributes 0..3 twice (model axis size 2 everywhere)
    assert sorted(int(v) for v in out) == [2, 2, 3, 3, 4, 4, 5, 5]


def test_validate_mesh():
    mesh = T.make_mesh(data=8)
    with pytest.raises(ValueError, match="missing required"):
        T.validate_mesh(mesh, (T.EXPERT_AXIS,))
    T.validate_mesh(mesh, (T.DATA_AXIS, T.MODEL_AXIS))


def test_hybrid_mesh_falls_back_on_single_slice(hvd):
    """CPU devices report no slice_index → single slice → plain mesh."""
    from horovod_tpu.core.topology import make_hybrid_mesh, make_mesh

    got = make_hybrid_mesh(data=2, model=4)
    want = make_mesh(data=2, model=4)
    assert got.axis_names == want.axis_names
    assert got.devices.shape == want.devices.shape
    assert [d.id for d in got.devices.flat] == \
        [d.id for d in want.devices.flat]


class _FakeDev:
    def __init__(self, i, s):
        self.id = i
        self.slice_index = s
        self.process_index = s
        self.platform = "tpu"
        self.device_kind = "faketpu"
        self.coords = (i % 4, 0, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"FakeDev({self.id}, slice={self.slice_index})"


def test_hybrid_mesh_places_ici_axes_within_slices(hvd):
    """2 fake slices x 4 chips, data=2 x model=4: every model group (the
    per-layer ICI axis) must live inside one slice; the data axis crosses
    slices."""
    from horovod_tpu.core.topology import make_hybrid_mesh

    devs = [_FakeDev(i, i // 4) for i in range(8)]
    mesh = make_hybrid_mesh(data=2, model=4, devices=devs)
    assert mesh.axis_names == ("data", "pipe", "seq", "model")
    arr = mesh.devices.reshape(2, 4)  # [data, model]
    for d in range(2):
        slices = {dev.slice_index for dev in arr[d]}
        assert len(slices) == 1, f"model group crosses slices: {arr[d]}"


def test_hybrid_mesh_splits_dcn_axis_between_dcn_and_ici(hvd):
    """data=4 over 2 slices x 4 chips: a 2-way data factor crosses DCN and
    a 2-way factor stays on ICI (the standard multi-slice DP recipe)."""
    from horovod_tpu.core.topology import make_hybrid_mesh

    devs = [_FakeDev(i, i // 4) for i in range(8)]
    mesh = make_hybrid_mesh(data=4, model=2, devices=devs)
    arr = mesh.devices.reshape(4, 2)  # [data, model]
    for d in range(4):
        slices = {dev.slice_index for dev in arr[d]}
        assert len(slices) == 1, f"model group crosses slices: {arr[d]}"


def test_hybrid_mesh_validates_dcn_axes(hvd):
    from horovod_tpu.core.topology import make_hybrid_mesh

    devs = [_FakeDev(i, i // 4) for i in range(12)]  # 3 fake slices
    with pytest.raises(ValueError, match="tile the slices"):
        make_hybrid_mesh(data=4, model=3, devices=devs,
                         dcn_axes=("data",))
    devs8 = [_FakeDev(i, i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="not in mesh axes"):
        make_hybrid_mesh(data=2, model=4, devices=devs8,
                         dcn_axes=("expert",))


def test_hybrid_mesh_slice_map_layout(hvd):
    """Explicit slice_map drives the hybrid layout over REAL devices:
    8 CPU devices declared as 2 virtual slices, data=2 over DCN,
    model=4 inside a slice."""
    from horovod_tpu.core.topology import make_hybrid_mesh

    devs = jax.devices()[:8]
    smap = {d.id: i // 4 for i, d in enumerate(devs)}
    mesh = make_hybrid_mesh(data=2, model=4, devices=devs,
                            slice_map=smap)
    arr = mesh.devices.reshape(2, 4)
    for d in range(2):
        slices = {smap[dev.id] for dev in arr[d]}
        assert len(slices) == 1, f"model group crosses slices: {arr[d]}"
    # The two data rows live on different declared slices.
    assert {smap[arr[0, 0].id], smap[arr[1, 0].id]} == {0, 1}


def test_hybrid_mesh_trains_end_to_end(hvd):
    """Round-4 verdict item 6: a DCN x ICI hybrid mesh actually TRAINS —
    dp2-over-DCN x tp4-over-ICI transformer step on 8 real CPU devices
    declared as 2 virtual slices; loss is finite and decreases."""
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.core.topology import make_hybrid_mesh
    from horovod_tpu.models.transformer import (ParallelAxes,
                                                TransformerConfig,
                                                init_transformer,
                                                make_loss_fn,
                                                synthetic_lm_batch)
    from horovod_tpu.parallel.training import (make_parallel_train_step,
                                               shard_parallel_batch)

    devs = jax.devices()[:8]
    mesh = make_hybrid_mesh(data=2, model=4, devices=devs,
                            slice_map={d.id: i // 4
                                       for i, d in enumerate(devs)})
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=64)
    ax = ParallelAxes(data="data", model="model")
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1), 8, 16,
                                         cfg.vocab_size)
    loss_fn = make_loss_fn(cfg, ax, mesh_axes=mesh.axis_names)
    opt = optax.adam(1e-2)
    step = make_parallel_train_step(loss_fn, opt, mesh, P("data", None),
                                    donate=False)
    batch = shard_parallel_batch((tokens, targets), mesh, P("data", None))
    state = opt.init(params)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
