"""Test fixture: run everything on 8 virtual CPU devices.

TPU translation of the reference's multi-process-without-cluster trick
(`mpirun -np 2 pytest` on localhost CPU, reference .travis.yml:96-103):
``--xla_force_host_platform_device_count=8`` gives one process eight XLA
"replicas" so collective correctness runs anywhere (SURVEY.md §4).

This must happen before the first JAX backend use.  The container pins
``JAX_PLATFORMS=axon`` (single real TPU chip over a tunnel); tests force
the CPU platform in-process so they never touch — or wait on — the chip.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
# Keep test runs off the real TPU tunnel (see memory: axon-cpu-test-env).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# Keras 3 binds its backend at first import.  TF ships keras, so a test
# file importing tensorflow before test_keras_frontend.py would silently
# bind the TF backend and hand the keras frontend symbolic tf.Tensors;
# pin the JAX backend for every ordering.
os.environ.setdefault("KERAS_BACKEND", "jax")
# hvd-analyze lock-order detector on for the whole tier-1 suite (and,
# via env inheritance, every multi-process scenario it launches): any
# lock-acquisition cycle raises LockOrderError in whichever test first
# exhibits the ordering (analysis/lockorder.py).  Must be set before
# horovod_tpu creates its locks.
os.environ.setdefault("HVD_TPU_LOCK_CHECK", "1")
# XLA executable-launch counting on for the whole suite
# (utils/xla_dispatch.py): every megakernel launch is wrapped in a
# thread-local dispatch window, so the "exactly one executable per
# fusion group" contract is continuously accumulated on
# ops.megakernel.stats and asserted by tests/test_megakernel.py —
# eager-op creep inside the fused executor fails the suite, not just
# the dedicated test's scenario.
os.environ.setdefault("HVD_TPU_COUNT_DISPATCHES", "1")
# hvd-race: the lockset data-race detector + thread-role asserts
# (analysis/races.py, analysis/threads.py — the env also gates the
# race_checked descriptors, so it must be set before horovod_tpu
# defines its classes) and the donation-lifetime sanitizer
# (analysis/donation.py) armed suite-wide, like the lock-order
# detector above: a guarded-field access no single lock protects, a
# cross-role method entry, or a stale read of a donated buffer raises
# its named error in whichever test first exhibits it.
os.environ.setdefault("HVD_TPU_RACE_CHECK", "1")
os.environ.setdefault("HVD_TPU_DONATION_CHECK", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process spawns, "
        "interpret-mode pallas backward passes)")
    # The int64 wire-dtype tests intentionally run without jax_enable_x64
    # (values stay in int32 range); jax's truncation notice is expected.
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Explicitly requested dtype.*int64.*:UserWarning")


@pytest.fixture()
def hvd():
    """Initialized horovod_tpu over all 8 virtual devices; fresh per test."""
    import horovod_tpu as hvd

    hvd.init(devices=jax.devices())
    yield hvd
    hvd.shutdown()


@pytest.fixture()
def hvd2():
    """Initialized over a 2-device subset (matches the reference's
    mpirun -np 2 test topology)."""
    import horovod_tpu as hvd

    hvd.init(devices=jax.devices()[:2])
    yield hvd
    hvd.shutdown()
