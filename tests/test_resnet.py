"""ResNet model + distributed-training smoke tests (≙ the reference's
examples/keras_imagenet_resnet50.py exercised as CI integration,
.travis.yml:114-120 — shrunken shapes for CI speed)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.models.resnet import (ResNet18Thin, ResNet50, init_resnet,
                                       resnet_loss_fn, synthetic_imagenet)
from horovod_tpu.parallel.training import (make_train_step_with_state,
                                           shard_batch)


def test_resnet50_forward_shape(hvd):
    model = ResNet50(num_classes=1000)
    params, stats = init_resnet(model, image_size=64, batch_size=8)
    x = jnp.zeros((8, 64, 64, 3))
    logits = jax.jit(lambda p, s, x: model.apply(
        {"params": p, "batch_stats": s}, x, train=False))(params, stats, x)
    assert logits.shape == (8, 1000)
    assert logits.dtype == jnp.float32


def test_resnet_distributed_step(hvd):
    """One fused-psum train step over 8 replicas with BN state sync."""
    model = ResNet18Thin(num_classes=10)
    params, stats = init_resnet(model, image_size=32, batch_size=8)
    loss_fn = resnet_loss_fn(model)
    opt = optax.sgd(0.1, momentum=0.9)
    step = make_train_step_with_state(loss_fn, opt, donate=False)

    images, labels = synthetic_imagenet(16, image_size=32, num_classes=10)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    p, s, o, loss = step(params, stats, opt.init(params), batch)
    assert np.isfinite(float(loss))
    # BN stats actually moved and stayed finite.
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(s),
                        jax.tree_util.tree_leaves(stats)))
    assert moved


def test_resnet_training_converges_on_tiny_task(hvd):
    model = ResNet18Thin(num_classes=4)
    params, stats = init_resnet(model, image_size=32, batch_size=8)
    loss_fn = resnet_loss_fn(model, weight_decay=0.0)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_train_step_with_state(loss_fn, opt)

    images, labels = synthetic_imagenet(32, image_size=32, num_classes=4)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    first = None
    for i in range(15):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_space_to_depth_stem_trains(hvd):
    """The MLPerf space-to-depth stem variant: same output contract, the
    stem conv sees 12 input channels instead of 3."""
    model = ResNet18Thin(num_classes=4, space_to_depth=True)
    params, stats = init_resnet(model, image_size=32, batch_size=8)
    assert params["conv_init"]["kernel"].shape == (4, 4, 12, 16)
    loss_fn = resnet_loss_fn(model)
    opt = optax.sgd(0.1)
    step = make_train_step_with_state(loss_fn, opt, donate=False)
    images, labels = synthetic_imagenet(16, image_size=32, num_classes=4)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    _, _, _, loss = step(params, stats, opt.init(params), batch)
    assert np.isfinite(float(loss))
