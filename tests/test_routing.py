"""hvd-route: the fleet router tier (docs/routing.md).

The load-bearing test here is the chain-hash byte-identity gate: the
router derives prompt-header keys with ``routing/affinity.py`` and the
replica indexes pages with ``serving/kv_cache.py`` — if the two schemes
ever diverge (dtype, page alignment, fingerprint seed), affinity
routing silently goes cold with no error anywhere.  The rest covers the
router's scoring/failover state machine and the fleet autoscaler over
in-memory fake replicas (the same four-method client surface
``bench.py --mode routing`` simulates and ``HttpReplicaClient``
implements for real fleets).
"""

import pytest

from horovod_tpu.routing import affinity
from horovod_tpu.routing.autoscale import AutoscaleConfig, FleetAutoscaler
from horovod_tpu.routing.replica import ReplicaUnreachable
from horovod_tpu.routing.router import Router, RouterConfig

PAGE = 4
PPS = 4
FP = "fp-router-test"


def _complete(prompt, n):
    """The rolling-hash completion oracle of bench.py --mode routing:
    state is a pure fold over tokens-so-far, so a continuation from any
    partial point reproduces the uninterrupted rollout exactly — the
    same bitwise property the serving engine's greedy decode has."""
    s = 0
    for t in prompt:
        s = (s * 1103515245 + int(t) + 12345) & 0x7FFFFFFF
    out = []
    for _ in range(n):
        t = (s * 48271 + 11) % 251
        out.append(t)
        s = (s * 1103515245 + t + 12345) & 0x7FFFFFFF
    return out


class FakeReplica:
    """In-memory replica speaking the router's client surface."""

    def __init__(self, name, queue_depth=0, kv_free=64,
                 fingerprint=FP, ready=True):
        self.name = name
        self.queue_depth = queue_depth
        self.kv_free = kv_free
        self.fingerprint = fingerprint
        self.ready = ready
        self.prefix_index = []   # hex digests advertised in /healthz
        self.chains = []         # token chains for /prefixes and /drain
        self.drain_after = None  # emit this many tokens, then 503
        self.unreachable = False
        self.resumed = []
        self.generated = 0

    def health(self):
        if self.unreachable:
            raise ReplicaUnreachable(self.name)
        return 200, {"serving": {
            "ready": self.ready, "queue_depth": self.queue_depth,
            "kv_free_pages": self.kv_free, "kv_total_pages": 64,
            "page_size": PAGE, "pages_per_slot": PPS,
            "fingerprint": self.fingerprint,
            "prefix_index": list(self.prefix_index)}}

    def generate(self, payload, timeout=None):
        if self.unreachable:
            raise ReplicaUnreachable(self.name)
        self.generated += 1
        prompt = [int(t) for t in payload["tokens"]]
        m = int(payload["max_tokens"])
        if self.drain_after is not None:
            k = min(self.drain_after, m)
            self.drain_after = None
            self.ready = False
            return 503, {"tokens": _complete(prompt, k),
                         "finish_reason": "draining"}
        return 200, {"tokens": _complete(prompt, m),
                     "finish_reason": "length"}

    def drain(self):
        if self.unreachable:
            raise ReplicaUnreachable(self.name)
        self.ready = False
        return 200, {"requests": [],
                     "prefixes": [list(c) for c in self.chains]}

    def prefixes(self):
        return 200, {"prefixes": [list(c) for c in self.chains]}

    def resume(self, payload):
        self.resumed.append(payload)
        return 200, {"resumed": 0,
                     "seeded": len(payload.get("prefixes") or [])}


def _fleet(*reps):
    r = Router(RouterConfig(probe_base=0.0), sleep=lambda s: None)
    for rep in reps:
        r.add_replica(rep.name, rep)
    r.poll()
    return r


# -- satellite: chain-hash byte identity router <-> kv_cache --------------

def test_prompt_header_hashes_byte_identical_to_live_kv_cache():
    """The router-side header keys must be EXACTLY the keys a live
    PagedKVCache publishes and looks up — hex-decode the router's
    strings and compare them to the cache's index bytes."""
    from horovod_tpu.serving.kv_cache import PagedKVCache

    cache = PagedKVCache(n_layers=1, n_heads=1, head_dim=2,
                         max_slots=2, pages_per_slot=PPS,
                         page_size=PAGE, prefix_cache=True,
                         fingerprint=FP)
    tokens = [7, 3, 1, 4, 9, 2, 6, 8, 5, 0]  # 2 full pages + 2 tail

    # The raw scheme delegation, digest for digest.
    assert cache._chain_hashes(tokens, 2) == affinity.chain_hashes(
        FP.encode(), tokens, PAGE, 2)

    # Publish through the real slot path; the index keys must be the
    # router's published_page_hashes, byte for byte.
    cache.begin_slot(0, len(tokens))
    assert cache.publish_prefix(0, tokens) == 2
    published = affinity.published_page_hashes(FP.encode(), tokens,
                                               PAGE, PPS)
    assert len(published) == 2
    assert set(cache.export_prefix_hashes()) == set(published)
    assert set(cache._index) == {bytes.fromhex(h) for h in published}

    # The router's strict-prefix header bound mirrors lookup_prefix:
    # same page count hit on a warm lookup.
    header = affinity.prompt_header_hashes(FP.encode(), tokens,
                                           PAGE, PPS)
    assert len(cache.lookup_prefix(tokens)) == len(header) == 2

    # An exactly page-aligned prompt keeps one suffix token to prefill:
    # header is one page SHORTER than what the replica published.
    aligned = tokens[:8]
    assert len(affinity.prompt_header_hashes(FP.encode(), aligned,
                                             PAGE, PPS)) == 1
    assert len(cache.lookup_prefix(aligned)) == 1

    # Divergent fingerprint ⇒ disjoint keys (the seed is load-bearing).
    other = affinity.prompt_header_hashes(b"other-model", tokens,
                                          PAGE, PPS)
    assert not set(other) & set(header)


def test_prompt_header_hashes_edge_cases():
    fp = FP.encode()
    assert affinity.prompt_header_hashes(fp, [], PAGE, PPS) == []
    # Shorter than one page + suffix token: no header pages.
    assert affinity.prompt_header_hashes(fp, [1, 2, 3, 4], PAGE,
                                         PPS) == []
    # pages_per_slot caps the chain.
    long = list(range(6 * PAGE + 1))
    assert len(affinity.prompt_header_hashes(fp, long, PAGE, PPS)) == PPS
    # Chain property: a longer prompt's header extends the shorter's.
    a = affinity.prompt_header_hashes(fp, long[:9], PAGE, PPS)
    b = affinity.prompt_header_hashes(fp, long[:13], PAGE, PPS)
    assert b[:len(a)] == a


# -- router selection ------------------------------------------------------

def test_select_least_loaded():
    r0 = FakeReplica("r0", queue_depth=3)
    r1 = FakeReplica("r1", queue_depth=0)
    router = _fleet(r0, r1)
    name, affinity_pages = router.select([1, 2, 3, 4, 5])
    assert (name, affinity_pages) == ("r1", 0)


def test_select_affinity_outweighs_queue():
    prompt = list(range(2 * PAGE + 3))
    warm = affinity.prompt_header_hashes(FP.encode(), prompt, PAGE, PPS)
    r0 = FakeReplica("r0", queue_depth=1)
    r0.prefix_index = warm
    r1 = FakeReplica("r1", queue_depth=0)
    router = _fleet(r0, r1)
    name, pages = router.select(prompt)
    assert (name, pages) == ("r0", 2)  # score 1-2 < 0


def test_select_no_affinity_credit_for_foreign_fingerprint():
    prompt = list(range(2 * PAGE + 3))
    r0 = FakeReplica("r0", queue_depth=1, fingerprint="other-model")
    # Even advertising the right keys: a different model's pages are
    # not this prompt's KV.
    r0.prefix_index = affinity.prompt_header_hashes(
        FP.encode(), prompt, PAGE, PPS)
    r1 = FakeReplica("r1", queue_depth=0)
    router = _fleet(r1, r0)  # r1 polled config wins the fleet fp
    name, pages = router.select(prompt)
    assert (name, pages) == ("r1", 0)


def test_select_headroom_penalty_avoids_full_replica():
    r0 = FakeReplica("r0", queue_depth=0, kv_free=0)
    r1 = FakeReplica("r1", queue_depth=5)
    router = _fleet(r0, r1)
    name, _ = router.select(list(range(9)))
    assert name == "r1"


def test_select_deterministic_tie_break():
    r0 = FakeReplica("r0")
    r1 = FakeReplica("r1")
    router = _fleet(r0, r1)
    picks = {router.select([1, 2, 3, 4, 5])[0] for _ in range(5)}
    assert picks == {"r0"}  # name order breaks exact ties


# -- dispatch: failover + continuation merge -------------------------------

def test_dispatch_stamps_and_counts():
    r0 = FakeReplica("r0")
    router = _fleet(r0)
    status, resp = router.dispatch({"tokens": [5, 3, 8], "max_tokens": 6})
    assert status == 200
    assert resp["tokens"] == _complete([5, 3, 8], 6)
    assert resp["router"]["replica"] == "r0"
    assert resp["router"]["failovers"] == 0


def test_dispatch_drain_continuation_digest_identical():
    """A 503-with-partials mid-flight resubmits as a continuation; the
    merged completion must equal the uninterrupted single-replica
    rollout token for token."""
    prompt, m = [9, 1, 7, 7, 2], 12
    r0 = FakeReplica("r0")
    r0.drain_after = 5
    r1 = FakeReplica("r1", queue_depth=1)  # loses the first selection
    router = _fleet(r0, r1)
    status, resp = router.dispatch({"tokens": prompt, "max_tokens": m})
    assert status == 200
    assert resp["tokens"] == _complete(prompt, m)
    assert resp["router"]["replica"] == "r1"
    assert resp["router"]["resubmits"] == 1
    assert resp["router"]["failovers"] == 1
    assert router.replica_status()["r0"]["status"] == "draining"


def test_dispatch_unreachable_marks_dead_then_backoff_revives():
    now = [100.0]
    r0 = FakeReplica("r0")
    r1 = FakeReplica("r1", queue_depth=1)
    router = Router(RouterConfig(probe_base=0.0),
                    clock=lambda: now[0], sleep=lambda s: None)
    router.add_replica("r0", r0)
    router.add_replica("r1", r1)
    router.poll()
    r0.unreachable = True
    status, resp = router.dispatch({"tokens": [1, 2, 3],
                                    "max_tokens": 4})
    assert status == 200
    assert resp["router"]["replica"] == "r1"
    assert resp["router"]["failovers"] == 1
    assert router.replica_status()["r0"]["status"] == "dead"
    # Dead replicas are not re-probed before their backoff expires...
    r0.unreachable = False
    router.poll()
    assert router.replica_status()["r0"]["status"] == "dead"
    # ...and rejoin the fleet once it does.
    now[0] += 60.0
    router.poll()
    assert router.replica_status()["r0"]["status"] == "ready"


def test_dispatch_no_ready_replica_is_503():
    r0 = FakeReplica("r0", ready=False)
    router = _fleet(r0)
    status, resp = router.dispatch({"tokens": [1], "max_tokens": 2})
    assert status == 503
    assert "no ready replica" in resp["error"]


def test_dispatch_rejects_tokenless_payload():
    router = _fleet(FakeReplica("r0"))
    status, _ = router.dispatch({"max_tokens": 4})
    assert status == 400


def test_dispatch_optimistically_publishes_affinity():
    """After a 200 the router credits the replica with the prompt's
    full pages BEFORE the next health poll — the back-to-back warm
    path."""
    prompt = list(range(2 * PAGE + 1))
    r0 = FakeReplica("r0")
    r1 = FakeReplica("r1")
    router = _fleet(r0, r1)
    first, _ = router.select(prompt)
    router.dispatch({"tokens": prompt, "max_tokens": 4})
    name, pages = router.select(prompt)
    assert name == first
    assert pages == 2


def test_drain_replica_exports_and_stops_traffic():
    r0 = FakeReplica("r0")
    r0.chains = [[1, 2, 3, 4], [1, 2, 3, 4, 5, 6, 7, 8]]
    router = _fleet(r0)
    export = router.drain_replica("r0")
    assert export["prefixes"] == r0.chains
    router.poll()
    assert router.ready_count() == 0


# -- fleet autoscaler ------------------------------------------------------

def _autoscaler(router, cfg, launched, price=None, headroom=None):
    def launch(name):
        rep = FakeReplica(name)
        launched[name] = rep
        return rep

    return FleetAutoscaler(router, launch,
                           retire=lambda name: launched.pop(name, None),
                           cfg=cfg, price=price, headroom=headroom)


def test_autoscaler_scale_up_seeds_from_donor():
    r0 = FakeReplica("r0", queue_depth=10)
    r0.chains = [[1, 2, 3, 4, 5, 6, 7, 8]]
    r0.prefix_index = affinity.published_page_hashes(
        FP.encode(), r0.chains[0], PAGE, PPS)
    router = _fleet(r0)
    launched = {}
    scaler = _autoscaler(router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_load=4.0, down_load=0.5,
        sustain=2, cooldown=2), launched)
    assert scaler.observe() is None        # sustain tick 1
    assert scaler.observe() == "up:auto1"  # tick 2 fires
    assert "auto1" in router.replica_names()
    # The newcomer was ghost-seeded from the busiest survivor's index.
    assert launched["auto1"].resumed == [
        {"requests": [], "prefixes": r0.chains}]
    # Cooldown: the next tick is quiet even though r0 is still loaded.
    assert scaler.observe() is None


def test_autoscaler_planner_veto():
    r0 = FakeReplica("r0", queue_depth=10)
    router = _fleet(r0)
    scaler = _autoscaler(router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_load=4.0, sustain=1,
        cooldown=0), {},
        price=lambda: 2 * 1024, headroom=lambda: 1024)
    assert scaler.observe() == "veto:up"
    assert router.replica_names() == ["r0"]


def test_autoscaler_scale_down_drains_victim_and_donates():
    r0 = FakeReplica("r0")
    auto1 = FakeReplica("auto1")
    auto1.chains = [[5, 6, 7, 8, 9, 10, 11, 12]]
    router = _fleet(r0, auto1)
    launched = {"auto1": auto1}
    scaler = _autoscaler(router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, up_load=50.0, down_load=1.0,
        sustain=2, cooldown=1), launched)
    with scaler._lock:
        scaler._launched.append("auto1")  # as if this scaler booted it
    assert scaler.observe() is None
    assert scaler.observe() == "down:auto1"  # prefers its own boots
    assert router.replica_names() == ["r0"]
    assert "auto1" not in launched           # retire hook ran
    # The victim's warm chains were donated to the survivor.
    assert r0.resumed == [{"requests": [], "prefixes": auto1.chains}]


def test_autoscaler_never_below_min_or_with_dead_replica():
    r0 = FakeReplica("r0")
    router = _fleet(r0)
    scaler = _autoscaler(router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, down_load=1.0, sustain=1,
        cooldown=0), {})
    assert scaler.observe() is None  # total == min_replicas
    r1 = FakeReplica("r1")
    router.add_replica("r1", r1)
    router.poll()
    r1.unreachable = True
    router.poll()
    # A dead replica mid-failover is not overcapacity: no scale-down.
    assert scaler.observe() is None


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
