"""Torch frontend tests — the reference's test_torch.py matrix translated:
self-verifying collectives (allreduce == tensor * size, broadcast == root
tensor), async/poll/synchronize, DistributedOptimizer hook flow, and
broadcast_parameters (SURVEY.md §4)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402

import horovod_tpu.frontends.torch as hvd_t  # noqa: E402


@pytest.fixture()
def thvd():
    hvd_t.init(devices=jax.devices())
    yield hvd_t
    hvd_t.shutdown()


DTYPES = [torch.float32, torch.float64, torch.int32, torch.int64]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("dims", [1, 2, 3])
def test_allreduce_sum(thvd, dtype, dims):
    size = thvd.size()
    t = torch.ones(*([4] * dims)).to(dtype)
    out = thvd.allreduce(t, average=False)
    assert out.dtype == dtype
    assert torch.equal(out, t * size)
    # input untouched (out-of-place)
    assert torch.equal(t, torch.ones(*([4] * dims)).to(dtype))


def test_allreduce_average(thvd):
    t = torch.arange(12.0).reshape(3, 4)
    out = thvd.allreduce(t, average=True)
    assert torch.allclose(out, t)


def test_allreduce_inplace(thvd):
    size = thvd.size()
    t = torch.ones(5)
    ret = thvd.allreduce_(t, average=False)
    assert ret is t
    assert torch.equal(t, torch.full((5,), float(size)))


def test_allreduce_async_poll_synchronize(thvd):
    size = thvd.size()
    t = torch.ones(4)
    h = thvd.allreduce_async(t, average=False, name="async.t")
    assert thvd.poll(h) in (True, False)  # valid before synchronize
    out = thvd.synchronize(h)
    assert torch.equal(out, torch.full((4,), float(size)))
    # synchronize() is wait_and_clear (torch/mpi_ops.cc:326-332): the
    # handle is gone afterwards.
    import pytest as _pytest
    with _pytest.raises(ValueError, match="already been cleared"):
        thvd.poll(h)


def _poll_until_done(thvd, h, timeout=10.0):
    # poll() is non-blocking and does not drive the fusion queue; the
    # background tick (5 ms) launches the op, so give it wall-clock time.
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if thvd.poll(h):
            return
        time.sleep(0.002)
    raise AssertionError("handle never completed")


def test_inplace_poll_then_synchronize_identity(thvd):
    """synchronize after a poll-side write-back returns the ORIGINAL
    tensor object (the reference's identity contract,
    torch/mpi_ops.py:328-344), and repeated poll stays True."""
    size = thvd.size()
    t = torch.ones(4)
    h = thvd.allreduce_async_(t, average=False, name="poll.id")
    _poll_until_done(thvd, h)
    assert thvd.poll(h) is True  # idempotent after completion
    assert torch.equal(t, torch.full((4,), float(size)))  # written back
    out = thvd.synchronize(h)
    assert out is t


def test_inplace_fire_and_forget_pins_nothing(thvd):
    """A polled-to-completion in-place handle that is never synchronized
    releases the underlying handle (jax.Array un-pinned) and its record
    dies with the target tensor."""
    import gc

    from horovod_tpu.core import state as _state
    from horovod_tpu.frontends.torch import _inplace_targets

    mgr = _state.global_state().handle_manager
    base = mgr.live_count()
    t = torch.ones(4)
    h = thvd.allreduce_async_(t, average=False, name="fire.forget")
    _poll_until_done(thvd, h)
    assert mgr.live_count() == base  # released on poll, not synchronize
    assert h in _inplace_targets    # tiny weakref record remains
    del t
    gc.collect()
    assert h not in _inplace_targets  # evicted by the weakref callback
    with pytest.raises(ValueError, match="garbage-collected|already been"):
        thvd.synchronize(h)


def test_inplace_poll_then_synchronize_temporary_view(thvd):
    """p.data-style TEMPORARY view target: poll's write-back must keep
    the view alive (refcount heuristic) so a later synchronize still
    returns the result tensor — and the parameter storage is updated."""
    size = thvd.size()
    p = torch.nn.Parameter(torch.ones(4))
    h = thvd.allreduce_async_(p.data, average=False, name="view.t")
    _poll_until_done(thvd, h)
    out = thvd.synchronize(h)
    np.testing.assert_allclose(out.detach().numpy(),
                               np.full(4, float(size)))
    np.testing.assert_allclose(p.detach().numpy(),
                               np.full(4, float(size)))


def test_inplace_poll_synchronize_after_target_dropped(thvd):
    """Target GC'd between poll-completion and synchronize: the result
    went with the tensor, so synchronize raises a clear error."""
    import gc

    t = torch.ones(4)
    h = thvd.allreduce_async_(t, average=False, name="poll.dropped")
    _poll_until_done(thvd, h)
    tid = id(t)
    del t
    gc.collect()
    del tid
    with pytest.raises(ValueError):
        thvd.synchronize(h)


def test_allgather(thvd):
    size = thvd.size()
    t = torch.arange(6).reshape(3, 2)
    out = thvd.allgather(t)
    assert out.shape == (3 * size, 2)
    for r in range(size):
        assert torch.equal(out[r * 3:(r + 1) * 3], t)


def test_broadcast(thvd):
    t = torch.arange(8.0)
    out = thvd.broadcast(t, root_rank=0)
    assert torch.equal(out, t)
    t2 = torch.zeros(3, dtype=torch.int32)
    ret = thvd.broadcast_(t2, 0)
    assert ret is t2


def test_broadcast_parameters(thvd):
    model = torch.nn.Linear(4, 2)
    sd = model.state_dict()
    hvd_t.broadcast_parameters(sd, root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.equal(v, sd[k])


def test_distributed_optimizer_trains(thvd):
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                                torch.nn.Linear(8, 1))
    hvd_t.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd_t.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    x = torch.randn(16, 4)
    w = torch.randn(4, 1)
    y = x @ w

    losses = []
    for _ in range(20):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_distributed_optimizer_hooks_fire(thvd):
    model = torch.nn.Linear(2, 1)
    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    loss = model(torch.ones(1, 2)).sum()
    loss.backward()
    # hooks fired during backward -> pending handles exist before step()
    assert len(opt._handles) == 2  # weight + bias
    opt.step()
    assert len(opt._handles) == 0


def test_noncontiguous_input(thvd):
    size = thvd.size()
    t = torch.arange(12.0).reshape(3, 4).t()  # non-contiguous view
    out = thvd.allreduce(t, average=False)
    assert torch.equal(out, t * size)


def test_gpu_tensor_rejected(thvd):
    if torch.cuda.is_available():  # pragma: no cover - CPU image
        t = torch.ones(2, device="cuda")
        with pytest.raises(ValueError, match="CPU"):
            thvd.allreduce(t)
    else:
        assert True


def test_rank_size_surface(thvd):
    assert thvd.size() == len(jax.devices())
    assert thvd.rank() == 0
    assert thvd.local_rank() == 0
    assert thvd.mpi_threads_supported() is True


def test_torch_allreduce_op_kwarg(hvd):
    """The post-v0.13 op= kwarg on the torch surface: Min/Max/Adasum
    reduce CPU torch tensors through the same wire as average/sum."""
    import horovod_tpu.frontends.torch as thvd

    t = torch.tensor([3.0, -1.0])
    np.testing.assert_allclose(
        thvd.allreduce(t, op=hvd.Min).numpy(), [3.0, -1.0])
    np.testing.assert_allclose(
        thvd.allreduce(t, op=hvd.Max).numpy(), [3.0, -1.0])
    # Replicated contributions: adasum is idempotent, product is x**n.
    np.testing.assert_allclose(
        thvd.allreduce(t, op=hvd.Adasum).numpy(), [3.0, -1.0], rtol=1e-6)
    np.testing.assert_allclose(
        thvd.allreduce(torch.tensor([2.0]), op=hvd.Product).numpy(),
        [2.0 ** hvd.size()])
    with pytest.raises(ValueError, match="not both"):
        thvd.allreduce(t, average=True, op=hvd.Sum)


def test_broadcast_optimizer_state(hvd):
    """broadcast_optimizer_state syncs the full state_dict — including
    lazily-created momentum buffers the reference needed workarounds
    for (post-v0.13 hvd.broadcast_optimizer_state)."""
    import horovod_tpu.frontends.torch as thvd

    model = torch.nn.Linear(3, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.5, momentum=0.9)
    # Create momentum buffers, then perturb the hyperparameters so the
    # broadcast has something real to restore.
    loss = model(torch.ones(1, 3)).sum()
    loss.backward()
    opt.step()
    want = {k: v for k, v in opt.state_dict()["param_groups"][0].items()}
    opt.param_groups[0]["lr"] = 123.0  # divergent non-root state
    thvd.broadcast_optimizer_state(opt, root_rank=0)
    # Single-process: rank 0 IS the root, so the state round-trips the
    # object wire and lands unchanged — including the mutated lr on the
    # root (the broadcast ships the CURRENT root state).
    assert opt.param_groups[0]["lr"] == 123.0
    # Momentum buffers survive the round trip tensor-identical.
    sd = opt.state_dict()
    assert any("momentum_buffer" in st for st in sd["state"].values())
    # The wrapped DistributedOptimizer delegates to the inner optimizer.
    dopt = thvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    thvd.broadcast_optimizer_state(dopt, root_rank=0)
    assert opt.param_groups[0]["lr"] == 123.0


def test_feature_query_shims(hvd):
    import horovod_tpu as H
    import horovod_tpu.frontends.torch as thvd

    assert not H.mpi_built() and not H.nccl_built()
    assert not H.cuda_built() and not H.gloo_built()
    assert H.xla_built()
    assert isinstance(H.native_built(), bool)
    assert thvd.mpi_built() is False  # same shims on the frontends


def test_sync_batch_norm_matches_local_bn_single_process(hvd):
    """Single-process, the global statistics reduce to the local ones
    (every replica contributes the identical batch), so SyncBatchNorm
    must match stock BatchNorm1d exactly — forward, backward, and
    running statistics."""
    import horovod_tpu.frontends.torch as thvd

    torch.manual_seed(0)
    x = torch.randn(16, 4, requires_grad=True)
    x2 = x.detach().clone().requires_grad_(True)

    sbn = thvd.SyncBatchNorm(4, momentum=0.3)
    bn = torch.nn.BatchNorm1d(4, momentum=0.3)
    bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})

    out_s = sbn(x)
    out_r = bn(x2)
    np.testing.assert_allclose(out_s.detach().numpy(),
                               out_r.detach().numpy(), atol=1e-5)
    g = torch.randn_like(out_s)
    out_s.backward(g)
    out_r.backward(g)
    np.testing.assert_allclose(x.grad.numpy(), x2.grad.numpy(), atol=1e-5)
    np.testing.assert_allclose(sbn.weight.grad.numpy(),
                               bn.weight.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(sbn.bias.grad.numpy(),
                               bn.bias.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(sbn.running_mean.numpy(),
                               bn.running_mean.numpy(), atol=1e-5)
    # The unbiased-variance correction uses the GLOBAL row count
    # (n = replicas x local rows — correct for real sharded batches);
    # stock BN uses the local 16.  Rescale to compare.
    n_local, n_glob = 16.0, 16.0 * hvd.size()
    scale = (n_glob / (n_glob - 1)) / (n_local / (n_local - 1))
    base = 1.0 - 0.3  # init running_var=1, one update at momentum 0.3
    want = (bn.running_var.numpy() - base) * scale + base
    np.testing.assert_allclose(sbn.running_var.numpy(), want, atol=1e-5)
    # Eval mode uses the running statistics (stock path).
    sbn.eval()
    out_eval = sbn(x.detach())
    assert torch.isfinite(out_eval).all()


def test_sync_batch_norm_fp16_stats_do_not_overflow(hvd):
    """fp16 inputs: the moments must accumulate in float32 — a straight
    fp16 sum-of-squares overflows past ~65504 (here Σx² ≈ 1.6e6 per
    channel) and the fp16 count loses integer precision above 2048."""
    import horovod_tpu.frontends.torch as thvd

    torch.manual_seed(1)
    base = 20.0 + 0.5 * torch.randn(4096, 4)
    x = base.half().requires_grad_(True)

    sbn = thvd.SyncBatchNorm(4).half()
    out = sbn(x)
    assert out.dtype == torch.float16
    assert torch.isfinite(out).all()
    # float32 reference over the same (fp16-quantized) inputs; residual
    # error is the fp16 normalization arithmetic itself.
    ref = torch.nn.BatchNorm1d(4)(x.detach().float())
    np.testing.assert_allclose(out.detach().float().numpy(),
                               ref.detach().numpy(), atol=5e-2)
    out.backward(torch.ones_like(out))
    assert torch.isfinite(x.grad).all()
    assert torch.isfinite(sbn.weight.grad).all()
    assert sbn.weight.grad.dtype == torch.float16
    assert torch.isfinite(sbn.running_var).all()
    np.testing.assert_allclose(sbn.running_mean.float().numpy(),
                               0.1 * base.mean(0).numpy(), atol=2e-2)


def test_sync_batch_norm_momentum_none_cumulative(hvd):
    """momentum=None is stock _BatchNorm's cumulative-moving-average mode
    (factor = 1/num_batches_tracked); it must not crash and the first
    update must overwrite the init stats entirely (factor 1.0)."""
    import horovod_tpu.frontends.torch as thvd

    torch.manual_seed(2)
    x = torch.randn(64, 3) * 2.0 + 5.0
    sbn = thvd.SyncBatchNorm(3, momentum=None)
    sbn(x)
    assert int(sbn.num_batches_tracked) == 1
    np.testing.assert_allclose(sbn.running_mean.numpy(),
                               x.mean(0).numpy(), atol=1e-4)
    sbn(x)  # second update: factor 1/2, stats stay at the batch moments
    np.testing.assert_allclose(sbn.running_mean.numpy(),
                               x.mean(0).numpy(), atol=1e-4)
    sbn.eval()
    assert torch.isfinite(sbn(x)).all()
