"""Tree-structured control-plane overlay (ops/tree.py).

Unit coverage of the layout math, the merged wire formats and the
root-side aggregation equivalence, plus an np=3 REAL-process leg
(controller + interior + leaf over TCP loopback, no XLA — the chaos cp
fleet machinery) asserting the tree's negotiation results are
byte-identical to the flat star's and that cache replicas stay
index-aligned across an interior merge.
"""

import math
import os
import socket
import struct

import pytest

from horovod_tpu.ops import cache as cache_mod
from horovod_tpu.ops import transport as T
from horovod_tpu.ops import tree
from horovod_tpu.ops import wire
from horovod_tpu.ops.wire import Request


def _req(rank, name, shape=(8,)):
    return Request(rank, wire.RequestType.ALLREDUCE,
                   wire.DataType.FLOAT32, name, -1, -1, shape,
                   wire.ReduceOp.SUM, 0, ())


# ---------------------------------------------------------------------------
# Layout
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [2, 3, 4, 7, 8, 9, 17, 64, 256, 1024])
@pytest.mark.parametrize("fanout", [1, 2, 4, 8])
def test_layout_invariants(world, fanout):
    layout = tree.build_layout(world, fanout)
    assert layout.order[0] == 0
    assert sorted(layout.order) == list(range(world))
    seen = set()
    for r in range(world):
        assert len(layout.children(r)) <= fanout
        # every rank walks up to the root without cycles
        hops = 0
        cur = r
        while cur != 0:
            cur = layout.parent(cur)
            hops += 1
            assert hops <= world
        seen.add(r)
        if fanout > 1:
            assert hops <= math.ceil(math.log(max(world, 2), fanout)) + 1
    assert seen == set(range(world))
    # subtrees partition the world under the root
    covered = [0]
    for c in layout.children(0):
        covered.extend(layout.subtree(c))
    assert sorted(covered) == list(range(world))


def test_layout_slice_major_ordering(monkeypatch):
    # 8 ranks, 2 virtual slices: subtrees must nest inside slices —
    # the ICI x DCN contract replica_hierarchy applies to the data
    # plane, applied here to the control plane's tree shape.
    monkeypatch.setenv("HVD_TPU_VIRTUAL_SLICES", "2")
    layout = tree.build_layout(8, 2)
    # slice 0 = ranks 0..3, slice 1 = ranks 4..7; the order visits
    # slice 0 (sans root) before slice 1
    rest = [r for r in layout.order[1:]]
    assert rest == sorted(rest, key=lambda r: (r // 4, r))


def test_root_frames_drop_from_linear_to_fanout_log():
    for world in (64, 256, 1024):
        stats = tree.simulate_cycle_frames(world, 8)
        flat = stats["flat_frames_per_cycle"]
        got = stats["tree_frames_per_cycle"]
        bound = 2 * 8 * max(1, math.ceil(math.log(world, 8)))
        assert got <= bound, (world, got, bound)
        assert got < flat / 4
        assert stats["tree_frames_per_pull"] == got


def test_tree_active_modes(monkeypatch):
    monkeypatch.setenv(tree.TREE_ENV, "off")
    assert not tree.tree_active(4096)
    monkeypatch.setenv(tree.TREE_ENV, "on")
    assert tree.tree_active(3)
    assert not tree.tree_active(2)  # a 2-rank "tree" IS the star
    monkeypatch.setenv(tree.TREE_ENV, "auto")
    monkeypatch.setenv(tree.THRESHOLD_ENV, "16")
    assert not tree.tree_active(15)
    assert tree.tree_active(16)


def test_validate_env_rejects_typos(monkeypatch):
    monkeypatch.setenv(tree.TREE_ENV, "sometimes")
    with pytest.raises(ValueError, match="auto, on or off"):
        tree.validate_env()
    monkeypatch.setenv(tree.TREE_ENV, "auto")
    monkeypatch.setenv(tree.FANOUT_ENV, "0")
    with pytest.raises(ValueError, match="expected >= 1"):
        tree.validate_env()


# ---------------------------------------------------------------------------
# Wire round trips
# ---------------------------------------------------------------------------

def test_hello_topo_roundtrip():
    entries = [(3, "hostA", "K=a;L=b"), (5, "hostB", "K=a;L=b")]
    assert tree.parse_hello_tree(tree.pack_hello_tree(entries)) == entries
    topo = [(3, T.Topology(0, 2, 1, 2)), (5, T.Topology(1, 2, 1, 2))]
    flag, parsed = tree.parse_topo_tree(tree.pack_topo_tree(1, topo))
    assert flag == 1
    assert parsed == dict(topo)


def test_merged_pull_roundtrip():
    entries = [(1, b'{"a": 1}'), (2, b"[]"), (7, b"")]
    rnd, out = tree.parse_merged_pull(tree.pack_merged_pull(42, entries))
    assert rnd == 42 and out == entries


def test_request_batch_parse_is_byte_exact():
    # Build a flat FRAME_REQUEST_BATCH payload the way the worker does.
    reqs = [_req(2, "a"), _req(2, "b", shape=(4, 4))]
    idxs = [0, 3, 9]
    arr = bytearray(max(idxs) // 8 + 1)
    for b in idxs:
        arr[b // 8] |= 1 << (b % 8)
    bitvec = bytes(arr)
    blob = b"".join(r.pack() for r in reqs)
    payload = (struct.pack("<iII", 2, 5, len(bitvec)) + bitvec
               + struct.pack("<H", len(reqs)) + blob + b"\x00" * 16)
    rank, epoch, got_idxs, blobs, ctx = tree.parse_request_batch(payload)
    assert (rank, epoch) == (2, 5)
    assert got_idxs == idxs
    assert b"".join(blobs) == blob
    assert len(ctx) == 16
    # re-parsed requests are field-identical
    for raw, orig in zip(blobs, reqs):
        back, _ = Request.unpack(raw)
        assert back.tensor_name == orig.tensor_name
        assert tuple(back.tensor_shape) == tuple(orig.tensor_shape)


def test_subtree_batch_roundtrip_and_grouping():
    items = [
        ("bits", 1, (2,), (0, 1)),
        ("bits", 1, (3,), (0, 1)),     # same entries -> same group
        ("bits", 2, (4,), (0,)),       # different epoch -> own group
        ("reqs", 2, [_req(2, "x").pack()]),
        ("arrival", 3, b"\x01" * 16),
    ]
    bits, reqs, arrivals = tree.merge_batch_items(items)
    assert bits == [(1, (2, 3), (0, 1)), (2, (4,), (0,))]
    payload = tree.pack_subtree_batch(bits, reqs, arrivals,
                                      {2: 7, 3: 9})
    secs = list(tree.iter_subtree_sections(payload))
    kinds = [s[0] for s in secs]
    assert kinds == ["bits", "bits", "reqs", "arrival", "counts"]
    assert secs[0][1:] == (1, [2, 3], [0, 1])
    assert secs[1][1:] == (2, [4], [0])
    assert secs[2][1] == 2 and secs[2][2][0].tensor_name == "x"
    assert secs[3][1] == 3 and secs[3][2] is not None
    assert secs[4][1] == {2: 7, 3: 9}


def test_merged_envelope_drives_cache_like_flat_bits():
    """Root-side equivalence: feeding a whole subtree's steady-state
    envelope through the section iterator accounts the IDENTICAL
    per-rank hits the flat per-rank frames would — same entries ready,
    same pending sets (cache-replica alignment across the merge)."""
    def build_cache(ranks):
        cache = cache_mod.ResponseCache(rank=0)
        for name in ("g0", "g1"):
            cache.stage_negotiated(
                name, {rr: _req(rr, name) for rr in ranks})
            resp = wire.Response(
                wire.ResponseType.ALLREDUCE, tensor_names=[name],
                tensor_shapes=[(8,)],
                tensor_type=wire.DataType.FLOAT32)
            cache.observe_response(resp)
        return cache

    ranks = [0, 1, 2, 3, 4]
    layout = tree.build_layout(5, 2)
    epoch = 0
    idxs = [0, 1]

    flat = build_cache(ranks)
    for r in ranks:
        for i in idxs:
            assert flat.hit_from_wire(i, r, epoch) is None
    flat_ready = flat.take_ready(lambda _p: 1 << 20)

    merged = build_cache(ranks)
    for i in idxs:  # rank 0's own hits
        assert merged.hit_from_wire(i, 0, epoch) is None
    for child in layout.children(0):
        env = tree.steady_envelope(layout, child, epoch, idxs)
        for sec in tree.iter_subtree_sections(env):
            if sec[0] == "bits":
                _k, ep, rs, ii = sec
                for r in rs:
                    for i in ii:
                        assert merged.hit_from_wire(i, r, ep) is None
    merged_ready = merged.take_ready(lambda _p: 1 << 20)
    assert [r.tensor_names for r in flat_ready[0]] \
        == [r.tensor_names for r in merged_ready[0]]
    assert flat_ready[1:] == merged_ready[1:]


# ---------------------------------------------------------------------------
# np=3 real-process leg: flat vs tree byte identity
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_cp_fleet(extra_env):
    """One np=3 cp fleet pass (the chaos matrix machinery: real
    processes, real sockets, no XLA); returns {rank: result-line}."""
    from horovod_tpu.chaos import matrix as M

    s = M.Scenario("tree_identity", "cp", "complete", np=3, cap=120.0,
                   env=dict(extra_env))
    p = M._run_pass(s, faulted=False)
    assert p.rc == 0, f"fleet pass failed (rc={p.rc}):\n" \
        + "\n".join(p.output.splitlines()[-30:])
    assert sorted(p.results) == [0, 1, 2], p.results
    return p.results, p.output


def test_np3_tree_results_byte_identical_to_flat():
    """The tentpole contract: controller + interior + leaf (fanout=1
    chain) produce negotiation records BYTE-IDENTICAL to the flat
    star's, with the response cache replicas index-aligned across the
    interior's merged frames (a desync would abort the run), and the
    fleet metrics pull answered by every rank through the merged
    FRAME_METRICS_TREE path."""
    base = {"HVD_TPU_CHAOS_CP_STEPS": "12",
            "HVD_TPU_TREE_PORT_BASE": str(_free_port())}
    flat_results, _ = _run_cp_fleet({**base, "HVD_TPU_TREE": "off"})
    tree_results, tree_out = _run_cp_fleet(
        {**base, "HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "1"})
    # identical records on every rank, and tree == flat bit-for-bit
    assert tree_results == flat_results
    assert len(set(tree_results.values())) == 1


def test_np3_tree_direct_leaves_fanout8():
    """The other np=3 shape: fanout 8 puts BOTH workers directly under
    the root (tree mode with no interior).  Leaves speak the flat
    FRAME_REQUEST_BATCH their parent merges — here the parent IS the
    root, which must accept it alongside envelopes."""
    results, _ = _run_cp_fleet({
        "HVD_TPU_CHAOS_CP_STEPS": "8",
        "HVD_TPU_TREE_PORT_BASE": str(_free_port()),
        "HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "8"})
    assert len(set(results.values())) == 1


def test_np3_tree_memory_gauges_aggregate_exactly():
    """hvd-mem satellite: the np=3 FRAME_METRICS_TREE pull must carry
    the memory gauge family from EVERY rank through the interior's
    merge, with fleet min/max/mean exact.  Each cp rank seeds a
    rank-keyed ledger entry ((rank+1) MiB); the controller asserts the
    aggregated gauge per-rank values and min/max/mean bit-for-bit
    (chaos.matrix._check_mem_gauges _diags on any mismatch) and prints
    the CHAOS_MEMGAUGES marker only when exact."""
    results, out = _run_cp_fleet({
        "HVD_TPU_CHAOS_CP_STEPS": "12",
        "HVD_TPU_TREE_PORT_BASE": str(_free_port()),
        "HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "1"})
    assert len(set(results.values())) == 1
    assert "CHAOS_MEMGAUGES ranks=3 ok" in out


def test_np3_flat_memory_gauges_aggregate_exactly():
    """Same exactness contract over the flat FRAME_METRICS star — the
    baseline the tree merge must match."""
    _, out = _run_cp_fleet({
        "HVD_TPU_CHAOS_CP_STEPS": "12",
        "HVD_TPU_TREE_PORT_BASE": str(_free_port()),
        "HVD_TPU_TREE": "off"})
    assert "CHAOS_MEMGAUGES ranks=3 ok" in out


def test_np3_tree_cache_replicas_survive_interior_merge():
    """Cache-replica alignment: with the response cache ON (the fleet
    default) the steady state broadcasts compact FRAME_RESPONSE_BATCH
    index frames, which every rank — including the leaf BEHIND the
    interior — must rebuild from an index-aligned replica.  A replica
    desync fails the run loudly, so a green pass with replays IS the
    alignment proof; we additionally require replays actually happened
    on a worker."""
    base = {"HVD_TPU_CHAOS_CP_STEPS": "12",
            "HVD_TPU_TREE_PORT_BASE": str(_free_port()),
            "HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "1"}
    results, out = _run_cp_fleet(base)
    assert len(set(results.values())) == 1
    assert "replica desync" not in out
