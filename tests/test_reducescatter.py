"""Reducescatter (the post-v0.13 ``hvd.reducescatter``; the v0.13
reference has no reduce-scatter at all).  Self-verifying matrices in the
reference's style: result compared against numpy chunking of the sum.
"""

import jax.numpy as jnp
import numpy as np
import pytest


@pytest.mark.parametrize("dtype", ["float32", "int32", "bfloat16"])
def test_reducescatter_per_replica(hvd, dtype):
    n = hvd.size()
    base = np.arange(2 * n, dtype="float32")
    rows = np.stack([base + 10 * r for r in range(n)]).astype(dtype)
    out = np.asarray(hvd.reducescatter(hvd.shard(jnp.asarray(rows)),
                                       average=False))
    want = rows.astype("float32").sum(axis=0).astype(dtype)
    assert out.shape == (n, 2)
    np.testing.assert_allclose(
        out.astype("float32").reshape(-1), want.astype("float32"),
        rtol=1e-2 if dtype == "bfloat16" else 1e-6)


def test_reducescatter_average_and_replicated(hvd):
    n = hvd.size()
    x = jnp.arange(float(n * 3)).reshape(n * 3)
    out = np.asarray(hvd.reducescatter(x, average=True))
    # Replicated input: sum = n*x, averaged back to x, chunked per rank.
    np.testing.assert_allclose(out.reshape(-1), np.arange(n * 3.0))


def test_reducescatter_validation(hvd):
    with pytest.raises(ValueError, match="divisible"):
        hvd.reducescatter(jnp.ones((hvd.size() + 1,)))
    with pytest.raises(ValueError, match="Average/Sum"):
        hvd.reducescatter(jnp.ones((hvd.size(),)), op=hvd.Adasum)
    with pytest.raises(ValueError, match="not a list"):
        hvd.reducescatter([jnp.ones((2,))] * hvd.size())


def test_reducescatter_matches_allreduce_chunks(hvd):
    """reducescatter == allreduce then per-rank dim-0 chunking — the
    defining identity."""
    n = hvd.size()
    rows = jnp.asarray(np.random.RandomState(3).normal(
        size=(n, 4 * n)).astype("float32"))
    x = hvd.shard(rows)
    rs = np.asarray(hvd.reducescatter(x, average=False))
    ar = np.asarray(hvd.allreduce(x, average=False))[0]
    np.testing.assert_allclose(rs.reshape(-1), ar, rtol=1e-5)


def test_reducescatter_torch_frontend(hvd):
    import torch

    import horovod_tpu.frontends.torch as thvd

    n = hvd.size()
    out = thvd.reducescatter(torch.arange(2 * n, dtype=torch.float32),
                             average=False)
    # Replicated torch input: sum = n*x; single-process returns the
    # per-replica stack flattened row-major == n*x.
    np.testing.assert_allclose(
        out.numpy().reshape(-1), n * np.arange(2 * n, dtype="float32"))


def test_grouped_allgather_and_reducescatter(hvd):
    """The post-v0.13 grouped variants: one handle per tensor, order
    preserved, negotiated in one tick."""
    n = hvd.size()
    outs = hvd.grouped_allgather([jnp.ones((1, 2)), jnp.full((2, 2), 3.0)])
    assert np.asarray(outs[0]).shape == (n, 2)
    assert np.asarray(outs[1]).shape == (2 * n, 2)
    np.testing.assert_allclose(np.asarray(outs[1]), 3.0)
    outs = hvd.grouped_reducescatter(
        [jnp.arange(float(n)), jnp.arange(float(2 * n))], average=False)
    np.testing.assert_allclose(np.asarray(outs[0]).reshape(-1),
                               n * np.arange(float(n)))
    np.testing.assert_allclose(np.asarray(outs[1]).reshape(-1),
                               n * np.arange(float(2 * n)))
