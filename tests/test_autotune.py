"""Autotuner: explore-then-commit over (fusion_threshold, cycle_time).

≙ the post-v0.13 HOROVOD_AUTOTUNE subsystem (the v0.13 reference has
only static env vars, operations.cc:140, :1207-1210); the TPU redesign
(deterministic grid sweep instead of Bayesian opt) is argued in
horovod_tpu/utils/autotune.py.  Tests inject a fake clock so windows
close deterministically.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.utils.autotune import Autotuner


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _make(clock, thresholds, cycles, warmup=1, sample=1.0, log=None):
    applied = []
    tuner = Autotuner(lambda th, cy: applied.append((th, cy)),
                      thresholds=thresholds, cycles=cycles,
                      warmup_samples=warmup, sample_seconds=sample,
                      log_path=log, clock=clock)
    return tuner, applied


def test_explores_all_configs_then_commits_to_best():
    clock = _Clock()
    thresholds, cycles = [1024, 4096], [0.002, 0.01]
    tuner, applied = _make(clock, thresholds, cycles)
    # Byte rate per config: make (4096, 0.002) the clear winner.
    rates = {(1024, 0.002): 10, (1024, 0.01): 5,
             (4096, 0.002): 100, (4096, 0.01): 20}

    # Warmup window: bytes discarded.
    clock.t = 1.1
    tuner.record_bytes(999999)
    tuner.maybe_step()
    assert not tuner.done
    while not tuner.done:
        cfg = applied[-1]
        tuner.record_bytes(rates[cfg])
        clock.t += 1.0
        tuner.maybe_step()
    assert tuner.committed == (4096, 0.002)
    assert applied[-1] == (4096, 0.002)
    # Every config was tried exactly once before the commit.
    assert sorted(applied[:-1]) == sorted(rates.keys())


def test_log_records_samples_and_commit(tmp_path):
    clock = _Clock()
    log = str(tmp_path / "autotune.csv")
    tuner, applied = _make(clock, [512], [0.005], warmup=0, log=log)
    tuner.record_bytes(50)
    clock.t = 1.0
    tuner.maybe_step()
    assert tuner.done
    tuner.close()
    lines = open(log).read().splitlines()
    assert lines[0].startswith("score_bytes_per_sec")
    assert lines[1] == "50.0,512,0.005"
    assert lines[2].startswith("# committed,512")


def test_dormant_after_commit():
    clock = _Clock()
    tuner, applied = _make(clock, [512], [0.005], warmup=0)
    clock.t = 1.0
    tuner.maybe_step()
    assert tuner.done
    n = len(applied)
    clock.t = 50.0
    tuner.maybe_step()  # no further exploration or re-application
    assert len(applied) == n


def test_autotune_env_contract(monkeypatch, tmp_path):
    """HOROVOD_AUTOTUNE=1 activates the tuner at init; sample windows
    driven by real eager traffic re-tune the live coordinator's fusion
    threshold; HOROVOD_CYCLE_TIME seeds the tick."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.core import state as _state

    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_SAMPLE_SECONDS", "0.05")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.0")
    hvd.init(devices=jax.devices())
    try:
        st = _state.global_state()
        assert st.autotuner is not None
        assert st.tick_seconds == pytest.approx(0.002)
        import time as _time

        seen = set()
        deadline = _time.monotonic() + 60.0
        i = 0
        # Drive eager traffic until the sweep commits (15 windows x
        # 0.05 s; a fixed iteration count can finish before the windows
        # elapse on a fast box).
        while not st.autotuner.done and _time.monotonic() < deadline:
            hvd.allreduce(jnp.ones((8,)), name=f"tune.{i}",
                          average=False)
            seen.add(st.coordinator._impl.fusion_threshold)
            i += 1
        assert st.autotuner.done, "sweep did not finish"
        assert len(seen) > 1, "fusion threshold was never re-tuned"
        committed = st.autotuner.committed
        assert st.fusion_threshold_bytes == committed[0]
        assert st.tick_seconds == committed[1]
    finally:
        hvd.shutdown()
